"""Cross-session shared-prefix KV (engine shared_prefix path).

A fresh session whose prompt starts with rows resident in ANOTHER
slot's KV gets them by device copy instead of re-prefill. Correctness
bar: the copied-prefix session must produce the exact greedy stream a
cold engine would; the copy must be safe while the source is still
decoding; divergent prompts must never share.
"""

import asyncio

import jax

from fasttalk_tpu.engine.engine import GenerationParams, TPUEngine
from fasttalk_tpu.engine.tokenizer import ByteTokenizer
from fasttalk_tpu.models.configs import get_model_config
from fasttalk_tpu.models.llama import init_params
from fasttalk_tpu.utils.metrics import get_metrics

TINY = get_model_config("test-tiny")
GREEDY = dict(temperature=0.0, top_k=0, top_p=1.0)
SYSTEM = ("You are a terse voice assistant for a realtime app; answer "
          "in one short sentence and never speculate about anything.")


def _engine(params, shared=True) -> TPUEngine:
    eng = TPUEngine(TINY, params, ByteTokenizer(), num_slots=4,
                    max_len=512, prefill_chunk=64, seed=0,
                    shared_prefix=shared)
    eng.start()
    return eng


def _gen(eng, rid, prompt, n=24):
    async def run():
        text = ""
        async for ev in eng.generate(
                rid, f"s-{rid}",
                [{"role": "system", "content": SYSTEM},
                 {"role": "user", "content": prompt}],
                GenerationParams(max_tokens=n, **GREEDY)):
            if ev["type"] == "token":
                text += ev["text"]
            elif ev["type"] == "error":
                raise AssertionError(ev)
        return text

    return asyncio.run(run())


def test_shared_prefix_stream_identical_and_counted():
    params = init_params(TINY, jax.random.PRNGKey(3))
    cold = _engine(params, shared=False)
    try:
        _gen(cold, "a", "first question")
        ref_b = _gen(cold, "b", "second, different question")
    finally:
        cold.shutdown()

    eng = _engine(params, shared=True)
    try:
        _gen(eng, "a", "first question")
        shared_before = get_metrics().counter(
            "engine_shared_prefix_tokens_total").value
        got_b = _gen(eng, "b", "second, different question")
        shared_after = get_metrics().counter(
            "engine_shared_prefix_tokens_total").value
    finally:
        eng.shutdown()
    # Session b's system prompt was stamped from session a's slot...
    assert shared_after > shared_before
    # ...and the stream is exactly what a cold engine produces.
    assert got_b == ref_b


def test_shared_prefix_while_source_decoding():
    """Admitting B mid-way through A's generation: both streams match
    their cold-engine references (the copy reads only the source's
    stable prompt rows)."""
    params = init_params(TINY, jax.random.PRNGKey(4))

    async def pair(eng):
        texts = {"a": "", "b": ""}

        async def one(rid, prompt, delay):
            await asyncio.sleep(delay)
            async for ev in eng.generate(
                    rid, f"s-{rid}",
                    [{"role": "system", "content": SYSTEM},
                     {"role": "user", "content": prompt}],
                    GenerationParams(max_tokens=48, **GREEDY)):
                if ev["type"] == "token":
                    texts[rid] += ev["text"]
        await asyncio.gather(one("a", "alpha question", 0),
                             one("b", "beta question", 0.3))
        return texts

    cold = _engine(params, shared=False)
    try:
        ref = asyncio.run(pair(cold))
    finally:
        cold.shutdown()
    eng = _engine(params, shared=True)
    try:
        got = asyncio.run(pair(eng))
    finally:
        eng.shutdown()
    assert got == ref


def test_intra_batch_burst_shares_leader_prefix():
    """A cold-start burst of sessions with one long system prompt:
    the leader prefills fully, the rest get the prefix stamped by
    device copy — greedy streams identical to a no-sharing engine."""
    params = init_params(TINY, jax.random.PRNGKey(6))
    long_system = SYSTEM * 3  # ~370 byte-tokens: well past the 64 gate

    async def burst(eng):
        texts = {}

        async def one(i):
            out = ""
            async for ev in eng.generate(
                    f"r{i}", f"s{i}",
                    [{"role": "system", "content": long_system},
                     {"role": "user", "content": f"question {i}"}],
                    GenerationParams(max_tokens=16, **GREEDY)):
                if ev["type"] == "token":
                    out += ev["text"]
                elif ev["type"] == "error":
                    raise AssertionError(ev)
            texts[i] = out
        await asyncio.gather(*(one(i) for i in range(4)))
        return texts

    cold = TPUEngine(TINY, params, ByteTokenizer(), num_slots=4,
                     max_len=1024, prefill_chunk=512, seed=0,
                     shared_prefix=False)
    cold.start()
    try:
        ref = asyncio.run(burst(cold))
    finally:
        cold.shutdown()

    eng = TPUEngine(TINY, params, ByteTokenizer(), num_slots=4,
                    max_len=1024, prefill_chunk=512, seed=0,
                    shared_prefix=True)
    eng.start()
    before = get_metrics().counter(
        "engine_shared_prefix_tokens_total").value
    try:
        got = asyncio.run(burst(eng))
        shared = get_metrics().counter(
            "engine_shared_prefix_tokens_total").value - before
    finally:
        eng.shutdown()
    assert got == ref
    # Delta, not the cumulative global counter: earlier tests in this
    # module also increment it, which would mask a regression here.
    assert shared >= 3 * 64  # three members stamped a long prefix


def test_share_skipped_when_it_cannot_shrink_the_bucket():
    """Regression (review): two ~1000-token prompts sharing only a
    short prefix in a max_len=1024 engine — stamping would put a
    1024-bucket delta at a non-zero start (silent KV corruption via the
    clamped write) and save nothing (same bucket). The gate must skip
    sharing and both streams must match a cold engine."""
    params = init_params(TINY, jax.random.PRNGKey(7))
    common = "C" * 100
    prompts = [common + ch * 860 for ch in "ab"]

    async def burst(eng):
        outs = {}

        async def one(i):
            txt = ""
            async for ev in eng.generate(
                    f"r{i}", f"s{i}",
                    [{"role": "user", "content": prompts[i]}],
                    GenerationParams(max_tokens=8, **GREEDY)):
                if ev["type"] == "token":
                    txt += ev["text"]
                elif ev["type"] == "error":
                    raise AssertionError(ev)
            outs[i] = txt
        await asyncio.gather(one(0), one(1))
        return outs

    results = {}
    for shared in (False, True):
        eng = TPUEngine(TINY, params, ByteTokenizer(), num_slots=2,
                        max_len=1024, prefill_chunk=512, seed=0,
                        shared_prefix=shared)
        eng.start()
        before = get_metrics().counter(
            "engine_shared_prefix_tokens_total").value
        try:
            results[shared] = asyncio.run(burst(eng))
            results[f"count{shared}"] = get_metrics().counter(
                "engine_shared_prefix_tokens_total").value - before
        finally:
            eng.shutdown()
    assert results[True] == results[False]
    assert results["countTrue"] == 0  # gate declined the useless share


def test_best_shared_prefix_safe_after_divergence_truncation():
    """Regression: reuse_prefix truncates a slot's tokens on divergence;
    if kv_written stayed above len(tokens), best_shared_prefix's scan
    indexed past the list and crashed the engine thread (aborting every
    session)."""
    from fasttalk_tpu.engine.slots import SlotManager, _lcp

    sm = SlotManager(4, 512)
    a = sm.acquire("A")
    a.tokens = list(range(200))
    a.kv_written = 200
    n = sm.reuse_prefix(a, list(range(40)) + [999] * 30)
    assert n == 40
    assert a.kv_written == 40  # watermark must drop with the truncation
    b = sm.acquire("B")
    src, share = sm.best_shared_prefix(b, list(range(60)))
    assert src is a and share == 40

    # _lcp block comparison agrees with the naive scan at block edges.
    for la, lb, lim in ((300, 300, 299), (257, 300, 256), (10, 10, 9)):
        x = list(range(la))
        y = list(range(lb))
        y[lim // 2] = -1
        naive = next((i for i in range(min(lim, len(x), len(y)))
                      if x[i] != y[i]), min(lim, len(x), len(y)))
        assert _lcp(x, y, lim) == naive


def test_no_share_on_divergent_prompts():
    """Prompts that share fewer than min_len leading tokens do not
    trigger the copy path."""
    params = init_params(TINY, jax.random.PRNGKey(5))
    eng = _engine(params, shared=True)
    try:
        async def run(rid, sys_prompt):
            async for ev in eng.generate(
                    rid, f"s-{rid}",
                    [{"role": "system", "content": sys_prompt},
                     {"role": "user", "content": "hi"}],
                    GenerationParams(max_tokens=8, **GREEDY)):
                pass

        asyncio.run(run("a", "totally unrelated persona text here"))
        asyncio.run(run("b", "B" * 40))
        assert get_metrics().counter(
            "engine_shared_prefix_tokens_total").value == 0
    finally:
        eng.shutdown()
