#!/usr/bin/env python
"""Fetch a model checkpoint into MODEL_PATH for the in-tree engine.

Closes the acquisition gap VERDICT r2 named: the reference's stacks get
weights automatically (vLLM pulls into its HF cache volume,
docker-compose.vllm.yml:58-59; Ollama pulls into ollama_data,
docker-compose.gpu.yml:30-34), while this repo had a loader but no way
to GET a checkpoint. This script is that way:

    python scripts/fetch_model.py llama3.2:1b --dest /app/models
    python scripts/fetch_model.py llama3.2:1b --from-dir /mnt/ckpts/1b
    MODEL_PATH=/app/models python main.py websocket   # serves real weights

Model names are the serving names (utils/config LLM_MODEL); each maps
to its canonical HF repo (override with --repo for fine-tunes). Uses
huggingface_hub when importable (it ships with transformers), else a
plain-HTTPS fallback; ``--from-dir`` needs no network at all (air-gapped
hosts: rsync the checkpoint, then link it into the MODEL_PATH layout).

Destination layout matches models/loader.find_checkpoint_dir:
    <dest>/<model name with ':' -> '_'>/{*.safetensors, *.json}
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

# Serving name -> canonical HF repo. Instruct variants: this framework
# serves chat (reference parity), so the chat-tuned checkpoints are the
# right default.
DEFAULT_REPOS = {
    "llama3.2:1b": "meta-llama/Llama-3.2-1B-Instruct",
    "llama3.2:3b": "meta-llama/Llama-3.2-3B-Instruct",
    "llama3:8b": "meta-llama/Meta-Llama-3-8B-Instruct",
    "llama3.1:8b": "meta-llama/Llama-3.1-8B-Instruct",
    "llama3:70b": "meta-llama/Meta-Llama-3-70B-Instruct",
    "llama3.1:70b": "meta-llama/Llama-3.1-70B-Instruct",
    "qwen2.5:0.5b": "Qwen/Qwen2.5-0.5B-Instruct",
    "qwen2.5:1.5b": "Qwen/Qwen2.5-1.5B-Instruct",
    "qwen2.5:7b": "Qwen/Qwen2.5-7B-Instruct",
    "mistral:7b": "mistralai/Mistral-7B-Instruct-v0.3",
}

# What the loader + tokenizer actually read (models/loader.py,
# engine/tokenizer.py). Safetensors shards are discovered via the index.
WANTED_PATTERNS = ("*.safetensors", "*.safetensors.index.json",
                   "config.json", "generation_config.json",
                   "tokenizer.json", "tokenizer_config.json",
                   "special_tokens_map.json")
WANTED_SUFFIXES = (".safetensors", ".safetensors.index.json")
WANTED_NAMES = ("config.json", "generation_config.json", "tokenizer.json",
                "tokenizer_config.json", "special_tokens_map.json")


def dest_dir(dest_root: str, model: str) -> str:
    return os.path.join(dest_root, model.replace(":", "_"))


def wanted(filename: str) -> bool:
    base = os.path.basename(filename)
    return base in WANTED_NAMES or base.endswith(WANTED_SUFFIXES)


def link_from_dir(src: str, dst: str, copy: bool = False) -> list[str]:
    """Populate dst from a local checkpoint directory (hardlink when
    possible — a 70B checkpoint should not be duplicated on disk)."""
    os.makedirs(dst, exist_ok=True)
    placed = []
    for name in sorted(os.listdir(src)):
        if not wanted(name):
            continue
        s, d = os.path.join(src, name), os.path.join(dst, name)
        if os.path.exists(d):
            os.unlink(d)
        if copy:
            shutil.copy2(s, d)
        else:
            try:
                os.link(s, d)
            except OSError:  # cross-device: fall back to copy
                shutil.copy2(s, d)
        placed.append(name)
    if not any(n.endswith(".safetensors") for n in placed):
        raise SystemExit(f"no .safetensors files found in {src}")
    return placed


def fetch_hub(repo: str, dst: str, revision: str, token: str | None,
              ) -> list[str]:
    """Download via huggingface_hub (resumable, shard-aware)."""
    from huggingface_hub import snapshot_download

    snapshot_download(
        repo_id=repo, revision=revision, token=token, local_dir=dst,
        allow_patterns=list(WANTED_PATTERNS))
    return sorted(f for f in os.listdir(dst) if wanted(f))


def fetch_https(repo: str, dst: str, revision: str, token: str | None,
                endpoint: str = "https://huggingface.co") -> list[str]:
    """Plain-HTTPS fallback (no huggingface_hub): resolve the file list
    from the repo tree API, then stream each wanted file."""
    import urllib.request

    def get(url: str):
        req = urllib.request.Request(url)
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        return urllib.request.urlopen(req, timeout=60)

    with get(f"{endpoint}/api/models/{repo}/tree/{revision}") as r:
        tree = json.load(r)
    names = [e["path"] for e in tree
             if e.get("type") == "file" and wanted(e["path"])]
    if not names:
        raise SystemExit(f"repo {repo} lists no checkpoint files")
    os.makedirs(dst, exist_ok=True)
    for name in names:
        out = os.path.join(dst, os.path.basename(name))
        print(f"  fetching {name}...", flush=True)
        with get(f"{endpoint}/{repo}/resolve/{revision}/{name}") as r, \
                open(out + ".part", "wb") as f:
            shutil.copyfileobj(r, f, length=1 << 20)
        os.replace(out + ".part", out)
    return sorted(os.path.basename(n) for n in names)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("model", help="serving model name, e.g. llama3.2:1b")
    ap.add_argument("--dest", default=os.environ.get("MODEL_PATH",
                                                     "/app/models"),
                    help="MODEL_PATH root (default: $MODEL_PATH)")
    ap.add_argument("--repo", default=None,
                    help="HF repo id override (fine-tunes)")
    ap.add_argument("--revision", default="main")
    ap.add_argument("--token", default=os.environ.get("HF_TOKEN"),
                    help="HF access token (gated repos; default $HF_TOKEN)")
    ap.add_argument("--from-dir", default=None,
                    help="link/copy from a local checkpoint dir (offline)")
    ap.add_argument("--copy", action="store_true",
                    help="with --from-dir: copy instead of hardlink")
    args = ap.parse_args()

    from fasttalk_tpu.models.configs import get_model_config

    cfg = get_model_config(args.model)  # fail fast on unknown names
    dst = dest_dir(args.dest, cfg.name)

    if args.from_dir:
        placed = link_from_dir(args.from_dir, dst, copy=args.copy)
    else:
        repo = args.repo or DEFAULT_REPOS.get(cfg.name)
        if repo is None:
            raise SystemExit(
                f"no default repo for {cfg.name}; pass --repo")
        print(f"fetching {repo}@{args.revision} -> {dst}", flush=True)
        try:
            placed = fetch_hub(repo, dst, args.revision, args.token)
        except ImportError:
            placed = fetch_https(repo, dst, args.revision, args.token)

    total = sum(os.path.getsize(os.path.join(dst, f)) for f in placed)
    print(f"placed {len(placed)} files ({total / 2**30:.2f} GiB) in {dst}")
    print(f"serve with: MODEL_PATH={args.dest} LLM_MODEL={cfg.name} "
          "python main.py websocket")


if __name__ == "__main__":
    main()
