#!/usr/bin/env python3
"""Bench regression gate: a fresh bench JSON vs the committed
BENCH_r*.json trajectory.

Every growth round commits its bench result as ``BENCH_rNN.json``
(``{"n", "cmd", "rc", "tail", "parsed"}`` where ``parsed`` is the one
JSON line bench.py printed). That trajectory is the repo's performance
memory — r01 1172.8 -> r05 2526.2 tok/s — but nothing READ it: a
regression only surfaced when a human eyeballed two files. This gate
closes the loop:

    python bench.py > /tmp/fresh.json
    python scripts/bench_compare.py /tmp/fresh.json

classifies the fresh result's mode from its metric/unit (each
BENCH_MODE prints a distinctive headline), finds the committed
trajectory entries of the SAME mode, and applies that mode's named
threshold against the latest committed value. Non-zero exit on
regression, so CI can gate on it.

Named thresholds (direction-aware — a faster chaos MTTR is an
improvement, a faster tok/s headline is a regression):

  ws / engine / fleet / overload / roofline   tok/s, higher is better,
                                              regression below -5%
  multiturn / radix / chaos                   ms, lower is better,
                                              regression above +25%
  disagg                                      ITL p99 gain ratio,
                                              higher is better, below
                                              -25% (tail-latency
                                              derived, latency band)
  longctx / int4 / paged                      capacity ratios, higher
                                              is better, below -10%
  structured                                  overhead frac, must stay
                                              < 0.05 absolute
  profiler                                    on/off delta frac, must
                                              stay within |0.01|

Latency and ratio modes get looser bands than throughput: the
committed trajectory shows tok/s is stable run to run while TTFT-class
medians on a shared box swing tens of percent.

A fresh mode with no committed history PASSES with a note — the first
recording of a new mode is a baseline, not a regression. ``--smoke``
self-tests the gate against the committed trajectory (the latest entry
must pass against its own history; a synthetically halved one must
fail) without running any bench.

Stdlib only; no engine import, so it runs anywhere instantly.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# (mode, matcher(metric, unit), kind, threshold). First match wins.
# kind: "higher" — regression if value < latest * (1 - tol);
#       "lower"  — regression if value > latest * (1 + tol);
#       "abs"    — regression if |value| > tol (no history needed).
_MODES: tuple[tuple, ...] = (
    ("profiler",
     lambda m, u: m.startswith("continuous-profiler"), "abs", 0.01),
    ("structured",
     lambda m, u: m.startswith("structured"), "abs", 0.05),
    ("chaos", lambda m, u: m.startswith("chaos"), "lower", 0.25),
    ("multiturn",
     lambda m, u: m.startswith("multiturn"), "lower", 0.25),
    ("radix", lambda m, u: m.startswith("radix"), "lower", 0.25),
    # Decode ITL p99 gain ratio (role-split over mixed): higher is
    # better, and it is tail-latency derived so it gets the loose
    # latency-class band, not the throughput one.
    ("disagg", lambda m, u: m.startswith("disagg"), "higher", 0.25),
    ("longctx", lambda m, u: m.startswith("longctx"), "higher", 0.10),
    ("int4", lambda m, u: m.startswith("int4"), "higher", 0.10),
    ("paged", lambda m, u: m.startswith("paged"), "higher", 0.10),
    ("fleet", lambda m, u: m.startswith("fleet"), "higher", 0.05),
    ("overload", lambda m, u: m.startswith("overload"), "higher", 0.05),
    ("roofline", lambda m, u: m.startswith("roofline"), "higher", 0.05),
    # The default ws/engine headline: "WebSocket output tok/s, ..." /
    # "engine-seam output tok/s, ...". Last so the specific modes
    # above (also tok/s) never fall through to it.
    ("ws", lambda m, u: u == "tok/s" and "output tok/s" in m,
     "higher", 0.05),
)


def classify(parsed: dict) -> tuple[str, str, float] | None:
    """(mode, kind, threshold) for a bench headline, or None."""
    metric = str(parsed.get("metric", ""))
    unit = str(parsed.get("unit", ""))
    for mode, match, kind, tol in _MODES:
        if match(metric, unit):
            return mode, kind, tol
    return None


def load_parsed(path: str) -> dict:
    """The bench headline dict from either shape: a raw bench stdout
    JSON line ({"metric", "value", ...}) or a committed BENCH_r*.json
    wrapper ({"parsed": {...}}). '-' reads stdin."""
    if path == "-":
        raw = sys.stdin.read()
    else:
        with open(path) as f:
            raw = f.read()
    # Committed records are one pretty-printed document; bench stdout
    # captures may carry log noise around the headline line. Try the
    # whole document first, then the last line that parses as a JSON
    # object, same as the bench drivers do.
    d = None
    try:
        d = json.loads(raw)
    except json.JSONDecodeError:
        pass
    if not isinstance(d, dict):
        d = None
    for line in [] if d is not None else \
            reversed(raw.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                d = json.loads(line)
                break
            except json.JSONDecodeError:
                continue
    if d is None:
        raise SystemExit(f"bench_compare: no JSON object in {path}")
    if "parsed" in d and isinstance(d["parsed"], dict):
        d = d["parsed"]
    if "value" not in d:
        raise SystemExit(
            f"bench_compare: {path} has no 'value' field — not a "
            f"bench headline")
    return d


def load_history(pattern: str) -> list[tuple[str, dict]]:
    """[(filename, parsed)] for every committed bench record, oldest
    first (BENCH_r01 < BENCH_r02 < ... by name)."""
    out = []
    for p in sorted(glob.glob(pattern)):
        try:
            with open(p) as f:
                d = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        parsed = d.get("parsed")
        if isinstance(parsed, dict) and "value" in parsed:
            out.append((os.path.basename(p), parsed))
    return out


def compare(fresh: dict, history: list[tuple[str, dict]],
            out=sys.stdout) -> int:
    """Print the verdict; return a process exit code (0 pass,
    1 regression, 2 unclassifiable)."""
    cls = classify(fresh)
    if cls is None:
        print(f"FAIL unclassifiable headline: metric="
              f"{fresh.get('metric')!r} unit={fresh.get('unit')!r}",
              file=out)
        return 2
    mode, kind, tol = cls
    value = float(fresh["value"])

    same = [(name, p) for name, p in history
            if (classify(p) or ("", "", 0.0))[0] == mode]
    traj = " -> ".join(f"{p['value']:g}" for _, p in same) or "(none)"
    print(f"mode={mode} fresh={value:g} {fresh.get('unit', '')} "
          f"trajectory: {traj}", file=out)

    if kind == "abs":
        # Contract bound, not a trajectory diff: these headlines are
        # overhead fractions whose acceptance bar is absolute.
        if abs(value) > tol:
            print(f"FAIL {mode}: |{value:g}| exceeds the {tol:g} "
                  f"absolute bound", file=out)
            return 1
        print(f"PASS {mode}: |{value:g}| within the {tol:g} absolute "
              f"bound", file=out)
        return 0

    if not same:
        print(f"PASS {mode}: no committed history — fresh value "
              f"recorded as the baseline", file=out)
        return 0

    ref_name, ref = same[-1]
    ref_v = float(ref["value"])
    if kind == "higher":
        floor = ref_v * (1.0 - tol)
        if value < floor:
            print(f"FAIL {mode}: {value:g} is "
                  f"{(1 - value / ref_v):.1%} below {ref_name} "
                  f"({ref_v:g}); threshold {tol:.0%}", file=out)
            return 1
        print(f"PASS {mode}: {value:g} vs {ref_name} {ref_v:g} "
              f"(floor {floor:g}, threshold -{tol:.0%})", file=out)
        return 0
    # kind == "lower"
    ceil = ref_v * (1.0 + tol)
    if value > ceil:
        print(f"FAIL {mode}: {value:g} is "
              f"{(value / ref_v - 1):.1%} above {ref_name} "
              f"({ref_v:g}); threshold {tol:.0%}", file=out)
        return 1
    print(f"PASS {mode}: {value:g} vs {ref_name} {ref_v:g} "
          f"(ceiling {ceil:g}, threshold +{tol:.0%})", file=out)
    return 0


def smoke(pattern: str) -> int:
    """Self-test against the committed trajectory: the newest entry
    must pass vs its own history, a halved copy must fail, and the two
    absolute-bound modes must gate both directions."""
    history = load_history(pattern)
    if not history:
        print("bench_compare --smoke: no committed BENCH_r*.json "
              "found", file=sys.stderr)
        return 1
    latest = dict(history[-1][1])
    rc = compare(latest, history)
    if rc != 0:
        print("SMOKE FAIL: latest committed entry flagged against "
              "its own history", file=sys.stderr)
        return 1
    bad = dict(latest)
    bad["value"] = float(latest["value"]) * 0.5
    if compare(bad, history) != 1:
        print("SMOKE FAIL: a 50% throughput drop was not flagged",
              file=sys.stderr)
        return 1
    prof_ok = {"metric": "continuous-profiler overhead delta frac, x",
               "value": -0.004, "unit": "frac"}
    prof_bad = dict(prof_ok, value=0.03)
    if compare(prof_ok, history) != 0 or compare(prof_bad, history) != 1:
        print("SMOKE FAIL: profiler absolute bound misgated",
              file=sys.stderr)
        return 1
    mttr_ok = {"metric": "chaos engine-restart MTTR-to-first-token "
                         "p50 ms, x", "value": 100.0, "unit": "ms"}
    if compare(mttr_ok, history) != 0:
        print("SMOKE FAIL: chaos entry without history did not pass "
              "as a new baseline", file=sys.stderr)
        return 1
    print("SMOKE PASS: gate flags drops, honours absolute bounds, "
          "and records new modes as baselines")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("fresh", nargs="?",
                    help="fresh bench JSON (bench.py stdout or a "
                         "BENCH_r*.json; '-' for stdin)")
    ap.add_argument("--history",
                    default=os.path.join(REPO, "BENCH_r*.json"),
                    help="glob of committed bench records "
                         "(default: repo root BENCH_r*.json)")
    ap.add_argument("--smoke", action="store_true",
                    help="self-test the gate against the committed "
                         "trajectory; runs no bench")
    args = ap.parse_args()
    if args.smoke:
        return smoke(args.history)
    if not args.fresh:
        ap.error("fresh bench JSON path required (or --smoke)")
    return compare(load_parsed(args.fresh), load_history(args.history))


if __name__ == "__main__":
    sys.exit(main())
