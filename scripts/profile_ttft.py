"""TTFT breakdown: where the milliseconds go between a WebSocket
user_message and the first token frame (VERDICT r3 #1).

Runs the real server + engine on the real device, instruments the hops
by wrapping the product code (no product changes), and prints a
per-stage breakdown for 1 session and a concurrent burst:

  client_send -> server_recv   WS read + event-loop dispatch
  server_recv -> gen_entry     history build, task spawn
  gen_entry   -> submitted     tokenization + command enqueue
  submitted   -> admitted      engine-thread drain + burst coalescing
  admitted    -> prefill_disp  prefill group build + device dispatch
  prefill_disp-> first_ready   device prefill + first-token fetch land
  first_ready -> ws_sent       engine->loop queue hop + WS write
  ws_sent     -> client_recv   loopback + client read

Usage:  python scripts/profile_ttft.py [sessions] [--no-coalesce]
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PORT = int(os.environ.get("BENCH_PORT", "18641"))
SESSIONS = int(sys.argv[1]) if len(sys.argv) > 1 and sys.argv[1].isdigit() \
    else 16
PROMPT = ("You are a concise assistant for a realtime voice app. "
          "Explain, in plain language, how a systolic array multiplies "
          "matrices and why that favours large batched matmuls.")

# request_id -> {stage: t}
MARKS: dict[str, dict[str, float]] = {}
TRACE: list[tuple[float, str, str]] = []  # engine-thread event trace
# session_id -> request_id (first token emitted flag)
_FIRST_SENT: set[str] = set()


def mark(rid: str, stage: str) -> None:
    MARKS.setdefault(rid, {})[stage] = time.monotonic()


def instrument(engine, server_mod) -> None:
    from fasttalk_tpu.engine import engine as eng_mod

    real_generate = engine.generate

    async def generate(request_id, session_id, messages, params):
        mark(request_id, "gen_entry")
        agen = real_generate(request_id, session_id, messages, params)
        first = True
        async for ev in agen:
            if first and ev["type"] == "token":
                mark(request_id, "loop_got_token")
                first = False
            yield ev

    engine.generate = generate

    real_put = engine._commands.put

    def put(item):
        if isinstance(item, tuple) and item[0] == "submit":
            mark(item[1].request_id, "submitted")
        real_put(item)

    engine._commands.put = put

    real_group = engine._prefill_group

    def prefill_group(bucket, sub):
        t = time.monotonic()
        for req, _, _, _ in sub:
            MARKS.setdefault(req.request_id, {})["admitted"] = t
        out = real_group(bucket, sub)
        t = time.monotonic()
        for req, _, _, _ in sub:
            MARKS.setdefault(req.request_id, {})["prefill_disp"] = t
        TRACE.append((t, "prefill_returned", f"bucket={bucket} "
                      f"n={len(sub)}"))
        return out

    engine._prefill_group = prefill_group

    real_defer = engine._defer_first

    def defer(firsts_dev, entries):
        t_submit = time.monotonic()

        def fetch():
            t_start = time.monotonic()
            out = __import__("numpy").asarray(firsts_dev)
            t_end = time.monotonic()
            TRACE.append((t_end, "worker-fetch",
                          f"queued={(t_start - t_submit) * 1000:.1f}ms "
                          f"fetch={(t_end - t_start) * 1000:.1f}ms"))
            return out

        for _, _, req in entries:
            req.first_pending = True
        engine._pending_firsts.append(
            (engine._fetch_pool.submit(fetch), entries))

    engine._defer_first = defer

    real_consume = engine._consume_token

    def consume(req, tok):
        if req.first_token_at is None:
            mark(req.request_id, "first_ready")
        real_consume(req, tok)

    engine._consume_token = consume

    # Trace the firsts-drain mechanics: does is_ready() exist / when do
    # polls succeed / when does the blocking fetch start and end?
    real_drain = engine._drain_firsts

    def drain(block):
        if engine._pending_firsts:
            arr_dev, entries = engine._pending_firsts[0]
            rids = [r.request_id for _, _, r in entries]
            probe = getattr(arr_dev, "is_ready", None)
            state = "no-probe" if probe is None else \
                ("ready" if probe() else "pending")
            t0 = time.monotonic()
            real_drain(block)
            dt = (time.monotonic() - t0) * 1000
            if block or state != "pending" or dt > 1:
                TRACE.append((time.monotonic(), "drain",
                              f"block={block} state={state} "
                              f"dt={dt:.1f}ms n={len(rids)}"))
        else:
            real_drain(block)

    engine._drain_firsts = drain

    real_retire = engine._retire_oldest

    def retire():
        t0 = time.monotonic()
        real_retire()
        TRACE.append((time.monotonic(), "retire",
                      f"dt={(time.monotonic() - t0) * 1000:.1f}ms"))

    engine._retire_oldest = retire

    real_dispatch = engine._dispatch_decode

    def dispatch():
        real_dispatch()
        TRACE.append((time.monotonic(), "dispatch_decode", ""))

    engine._dispatch_decode = dispatch

    if "--block-firsts" in sys.argv:
        # Experiment: emit first tokens synchronously at the end of the
        # prefill (before any decode dispatch can hit the device queue),
        # with a probe fetch first to split compute from fetch channel.
        real_defer = engine._defer_first

        def defer(firsts_dev, entries):
            import numpy as _np

            t0 = time.monotonic()
            _np.asarray(engine._cur_tokens)  # data-dep on same prefill
            t1 = time.monotonic()
            _np.asarray(firsts_dev)
            t2 = time.monotonic()
            _np.asarray(firsts_dev)
            t3 = time.monotonic()
            TRACE.append((t3, "defer-block",
                          f"probe={(t1 - t0) * 1000:.1f}ms "
                          f"firsts={(t2 - t1) * 1000:.1f}ms "
                          f"refetch={(t3 - t2) * 1000:.1f}ms"))
            real_defer(firsts_dev, entries)
            engine._drain_firsts(block=True)

        engine._defer_first = defer


def patch_server(server) -> None:
    real_send = server._send

    async def send(session_id, ws, payload):
        await real_send(session_id, ws, payload)
        if payload.get("type") == "token" and session_id not in _FIRST_SENT:
            _FIRST_SENT.add(session_id)
            rid = server._cur_request.get(session_id)
            if rid:
                mark(rid, "ws_sent")
                MARKS[rid]["session_id"] = session_id  # type: ignore

    server._send = send

    real_user = server._handle_user_message

    async def handle_user(session_id, message, ws):
        MARKS.setdefault(f"sess:{session_id}", {})[
            "server_recv"] = time.monotonic()
        await real_user(session_id, message, ws)

    server._handle_user_message = handle_user


async def ws_session(http, i: int, max_tokens: int = 16) -> dict:
    async with http.ws_connect(f"ws://127.0.0.1:{PORT}/ws/llm") as ws:
        msg = json.loads((await ws.receive()).data)
        session_id = msg["session_id"]
        await ws.send_json({"type": "start_session",
                            "config": {"max_tokens": max_tokens}})
        await ws.receive()
        t0 = time.monotonic()
        await ws.send_json({"type": "user_message",
                            "text": f"[session {i}] {PROMPT}"})
        ttft = None
        while True:
            frame = json.loads((await ws.receive()).data)
            if frame["type"] == "token" and ttft is None:
                ttft = time.monotonic()
            elif frame["type"] == "response_complete":
                break
            elif frame["type"] == "error":
                raise RuntimeError(frame)
        await ws.send_json({"type": "end_session"})
        await ws.receive()
    return {"session_id": session_id, "client_send": t0,
            "client_recv": ttft}


STAGES = ["client_send", "server_recv", "gen_entry", "submitted",
          "admitted", "prefill_disp", "first_ready", "ws_sent",
          "client_recv"]


def report(label: str, rows: list[dict]) -> None:
    print(f"\n== {label} ({len(rows)} sessions) ==")
    deltas: dict[str, list[float]] = {}
    totals = []
    for r in rows:
        sid = r["session_id"]
        rid = next((k for k, v in MARKS.items()
                    if v.get("session_id") == sid), None)
        m = dict(MARKS.get(rid, {}))
        m.update(MARKS.get(f"sess:{sid}", {}))
        m["client_send"], m["client_recv"] = r["client_send"], r["client_recv"]
        prev_stage = None
        for st in STAGES:
            if st not in m:
                continue
            if prev_stage is not None:
                deltas.setdefault(f"{prev_stage:>12} -> {st}", []).append(
                    (m[st] - m[prev_stage]) * 1000)
            prev_stage = st
        totals.append((m["client_recv"] - m["client_send"]) * 1000)
    for name, vals in deltas.items():
        print(f"  {name:34s} p50 {statistics.median(vals):7.1f} ms   "
              f"max {max(vals):7.1f} ms")
    print(f"  {'TOTAL client TTFT':34s} p50 {statistics.median(totals):7.1f}"
          f" ms   max {max(totals):7.1f} ms")


async def main() -> None:
    import aiohttp
    from aiohttp import web

    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.serving import server as server_mod
    from fasttalk_tpu.serving.server import WebSocketLLMServer
    from fasttalk_tpu.utils.config import Config

    cfg = Config(llm_provider="tpu", model_name="llama3.2:1b",
                 decode_slots=SESSIONS, max_model_len=2048,
                 default_context_window=2048, prefill_chunk=512,
                 dtype="bfloat16", port=PORT, monitoring_port=PORT + 1,
                 enable_agent=False, quantize="int8")
    engine = build_engine(cfg)
    engine.warmup(cfg.warmup)
    engine.start()
    instrument(engine, server_mod)
    server = WebSocketLLMServer(cfg, engine, None)
    patch_server(server)
    runner = web.AppRunner(server.app)
    await runner.setup()
    await web.TCPSite(runner, "127.0.0.1", PORT).start()
    print("server up; warming protocol...", file=sys.stderr)

    try:
        async with aiohttp.ClientSession() as http:
            await ws_session(http, 990, 8)
            await asyncio.gather(*(ws_session(http, 900 + i, 8)
                                   for i in range(SESSIONS)))
            MARKS.clear()
            _FIRST_SENT.clear()

            singles = []
            for rep in range(5):
                singles.append(await ws_session(http, 100 + rep, 16))
            report("single session x5", singles)

            MARKS.clear()
            _FIRST_SENT.clear()
            TRACE.clear()
            await asyncio.sleep(2)  # let stale in-flight work fully drain
            t_burst = time.monotonic()
            burst = await asyncio.gather(
                *(ws_session(http, i, 16) for i in range(SESSIONS)))
            report(f"burst {SESSIONS}", list(burst))
            print("\n== engine-thread trace (burst, first 400ms) ==")
            for t, kind, detail in TRACE:
                dt = (t - t_burst) * 1000
                if dt < 400:
                    print(f"  +{dt:7.1f}ms {kind:16s} {detail}")
    finally:
        await runner.cleanup()
        engine.shutdown()


if __name__ == "__main__":
    asyncio.run(main())
