"""Record an end-to-end WebSocket transcript artifact.

Serves the configured model through the real stack (`main.py
websocket`'s app — engine, conversation manager, WS protocol), runs a
short two-turn conversation from a real client, and writes a markdown
transcript with every protocol frame type, the rendered stats, and the
environment facts (tokenizer source, weights provenance).

In the zero-egress hosting image, weights are random-init and the
bundled 32k BPE tokenizer is served — mechanics (template, EOS,
streaming, multi-turn KV reuse) are identical to real weights; text is
sampled from an untrained model and reads as fluent-tokenized noise.
With a real checkpoint under MODEL_PATH (scripts/fetch_model.py), the
same script records a coherent-text transcript unchanged.

Usage: python scripts/demo_transcript.py [--out docs/TRANSCRIPT.md]
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PORT = int(os.environ.get("BENCH_PORT", "18651"))
TURNS = json.loads(os.environ.get("DEMO_TURNS", "null")) or [
    "Hi! In one sentence, what does a systolic array do?",
    "And why does that favour large batched matmuls?",
]


async def record(cfg) -> list[dict]:
    import aiohttp

    from fasttalk_tpu.serving.local import start_local_server

    engine, runner = await start_local_server(cfg, with_agent=False)
    frames: list[dict] = []

    def note(direction: str, payload: dict) -> None:
        frames.append({"t": time.monotonic(), "dir": direction,
                       **payload})

    try:
        async with aiohttp.ClientSession() as http:
            async with http.ws_connect(
                    f"ws://127.0.0.1:{PORT}/ws/llm") as ws:
                note("recv", json.loads((await ws.receive()).data))
                cfg_msg = {"type": "start_session",
                           "config": {"max_tokens": 48,
                                      "temperature": 0.7}}
                await ws.send_json(cfg_msg)
                note("send", cfg_msg)
                note("recv", json.loads((await ws.receive()).data))
                for turn in TURNS:
                    msg = {"type": "user_message", "text": turn}
                    await ws.send_json(msg)
                    note("send", msg)
                    text = ""
                    while True:
                        m = json.loads((await ws.receive()).data)
                        if m["type"] == "token":
                            text += m["data"]
                        else:
                            note("recv", {"type": "token (aggregated)",
                                          "data": text})
                            note("recv", m)
                            break
                        if m["type"] == "error":
                            raise RuntimeError(m)
                await ws.send_json({"type": "end_session"})
                note("send", {"type": "end_session"})
                note("recv", json.loads((await ws.receive()).data))
        frames.append({"model_info": engine.get_model_info(),
                       "tokenizer": type(engine.tokenizer).__name__,
                       "tokenizer_vocab": engine.tokenizer.vocab_size})
    finally:
        await runner.cleanup()
        engine.shutdown()
    return frames


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="docs/TRANSCRIPT.md")
    args = ap.parse_args()

    from fasttalk_tpu.models.loader import find_checkpoint_dir
    from fasttalk_tpu.utils.config import Config

    cfg = Config(llm_provider="tpu",
                 model_name=os.environ.get("LLM_MODEL", "llama3.2:1b"),
                 port=PORT, monitoring_port=PORT + 1, enable_agent=False,
                 quantize=os.environ.get("TPU_QUANTIZE", "int8"),
                 max_model_len=2048, default_context_window=2048)
    ckpt = find_checkpoint_dir(cfg.model_path, cfg.model_name) \
        if cfg.model_path else None
    frames = asyncio.run(record(cfg))

    t0 = next(f["t"] for f in frames if "t" in f)
    meta = frames[-1]
    lines = [
        "# WebSocket serving transcript",
        "",
        f"Recorded by `scripts/demo_transcript.py` on "
        f"{time.strftime('%Y-%m-%d')} against the real serving stack "
        "(aiohttp WS server + in-process TPU engine) on a v5e-1.",
        "",
        f"- model: `{cfg.model_name}` — weights "
        + (f"loaded from `{ckpt}`" if ckpt else
           "**random-init** (zero-egress image: no checkpoint on disk; "
           "mechanics identical to real weights, text is untrained "
           "noise — see tests/test_real_checkpoint.py for the "
           "skipif-guarded real-weights path)"),
        f"- tokenizer: {meta['tokenizer']} "
        f"(vocab {meta['tokenizer_vocab']})",
        f"- engine: {json.dumps(meta['model_info'], default=str)}",
        "",
        "| t (ms) | dir | frame |",
        "|---|---|---|",
    ]
    for f in frames:
        if "t" not in f:
            continue
        body = {k: v for k, v in f.items() if k not in ("t", "dir")}
        txt = json.dumps(body, ensure_ascii=False)
        if len(txt) > 300:
            txt = txt[:300] + "…"
        txt = txt.replace("|", "\\|")
        lines.append(f"| {1000 * (f['t'] - t0):7.0f} | {f['dir']} "
                     f"| `{txt}` |")
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    print(f"wrote {args.out} ({len(frames) - 1} frames)")


if __name__ == "__main__":
    main()
