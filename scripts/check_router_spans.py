#!/usr/bin/env python3
"""Router span coverage lint (docs/OBSERVABILITY.md "Fleet tracing",
run_tests.sh --journey).

The fleet trace promise is that every router decision the chaos suite
can break is also VISIBLE in the stitched timeline: each router
failpoint seam has a span of the same name, and the full router span
vocabulary is exercised by the fleet-trace test suite. Statically
cross-checks three surfaces — no imports, pure AST/text, same
discipline as scripts/check_failpoints.py:

1. Every ``router.*`` failpoint in the CATALOG
   (fasttalk_tpu/resilience/failpoints.py) maps to a router span name
   in SEAM_SPANS below — a new chaos seam without a matching span
   would be a router decision the trace cannot see.
2. Every router span name (the SEAM_SPANS values plus the
   dispatch-lifecycle spans ``failover`` and ``resume``) is emitted by
   an AST-visible ``add_span``/``event``/``step`` call with that
   string literal somewhere under fasttalk_tpu/router/.
3. Every router span name is referenced by tests/test_fleet_trace.py
   — an unasserted span regresses silently.

Exit 0 = clean; exit 1 = problems, each printed on its own line.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
FAILPOINTS = REPO / "fasttalk_tpu" / "resilience" / "failpoints.py"
ROUTER_DIR = REPO / "fasttalk_tpu" / "router"
TRACE_TESTS = REPO / "tests" / "test_fleet_trace.py"

# router failpoint seam -> span name recorded at that seam. Check 1
# fails when the CATALOG grows a router.* point with no entry here;
# adding the entry forces adding the span (check 2) and its test
# (check 3).
SEAM_SPANS = {
    "router.place": "place",
    "router.probe": "probe",
    "router.migrate_send": "migrate_send",
    "router.migrate_recv": "migrate_recv",
    "router.handoff": "handoff",
}

# Spans with no failpoint seam of their own but part of the router's
# trace vocabulary: failover is observable via the router_failover
# event + the re-dispatched place span; resume marks the stream
# continuing on the survivor.
LIFECYCLE_SPANS = ("failover", "resume")

# Emitter methods whose first string-literal argument after request_id
# is a span/step/event name (observability/trace.py Tracer API), plus
# "span" — router/migrate.py wraps tracer.add_span in a local span()
# helper so both transfer legs share the guard logic.
EMITTERS = ("add_span", "event", "step", "span")


def router_catalog_points() -> set[str]:
    """router.* CATALOG keys, read from the AST (no import — same
    rationale as check_failpoints.catalog_names)."""
    tree = ast.parse(FAILPOINTS.read_text())
    for node in ast.walk(tree):
        if isinstance(node, (ast.AnnAssign, ast.Assign)):
            targets = ([node.target] if isinstance(node, ast.AnnAssign)
                       else node.targets)
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if "CATALOG" in names and isinstance(node.value, ast.Dict):
                return {k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and k.value.startswith("router.")}
    raise SystemExit(f"{FAILPOINTS}: CATALOG dict literal not found")


def emitted_span_names() -> dict[str, list[str]]:
    """span name -> router files that emit it via an AST-visible
    ``.add_span(...)``/``.event(...)``/``.step(...)`` call whose name
    argument is a string literal. ``step`` takes the name first;
    ``add_span``/``event`` take it second (after request_id) — accept
    a literal in either of the first two positions so the lint does
    not depend on call-shape details."""
    sites: dict[str, list[str]] = {}
    for path in sorted(ROUTER_DIR.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError as e:  # pragma: no cover
            print(f"PROBLEM: {path}: unparseable ({e})")
            sys.exit(1)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            name = func.attr if isinstance(func, ast.Attribute) \
                else func.id if isinstance(func, ast.Name) else None
            if name not in EMITTERS:
                continue
            for arg in node.args[:2]:
                if isinstance(arg, ast.Constant) \
                        and isinstance(arg.value, str):
                    sites.setdefault(arg.value, []).append(
                        str(path.relative_to(REPO)))
    return sites


def main() -> int:
    problems: list[str] = []

    # 1. every router.* failpoint seam has a span mapping
    points = router_catalog_points()
    for point in sorted(points - set(SEAM_SPANS)):
        problems.append(
            f"router failpoint {point!r} has no span mapping in "
            "scripts/check_router_spans.py SEAM_SPANS — a chaos seam "
            "the stitched trace cannot see")
    for point in sorted(set(SEAM_SPANS) - points):
        problems.append(
            f"SEAM_SPANS maps {point!r} which is not in the "
            "failpoints CATALOG (stale lint entry)")

    # 2. every span name is emitted somewhere under fasttalk_tpu/router/
    required = sorted(set(SEAM_SPANS.values()) | set(LIFECYCLE_SPANS))
    emitted = emitted_span_names()
    for span in required:
        if span not in emitted:
            problems.append(
                f"router span {span!r} is not emitted by any "
                "add_span/event/step string-literal call under "
                "fasttalk_tpu/router/")

    # 3. every span name is asserted by the fleet-trace suite
    if not TRACE_TESTS.exists():
        problems.append(f"{TRACE_TESTS} does not exist")
    else:
        text = TRACE_TESTS.read_text()
        for span in required:
            if f'"{span}"' not in text and f"'{span}'" not in text:
                problems.append(
                    f"router span {span!r} is not referenced by "
                    f"{TRACE_TESTS.relative_to(REPO)} (unasserted "
                    "span regresses silently)")

    if problems:
        for p in problems:
            print(f"PROBLEM: {p}")
        return 1
    print(f"check_router_spans: {len(points)} router failpoint seams "
          f"mapped, {len(required)} router spans all emitted in-tree "
          "and all asserted by tests/test_fleet_trace.py")
    return 0


if __name__ == "__main__":
    sys.exit(main())
