"""Train the in-repo tinychat model and export it as an HF checkpoint.

Closes VERDICT r4 missing #1: every prior transcript served random-init
noise because real checkpoints are unfetchable here (no egress — the
reference always mounted real weights, docker-compose.vllm.yml:58-59).
The repo owns a training stack, so this script trains a ~4M-param Llama
on the deterministic synthetic chat corpus (fasttalk_tpu/training/
corpus.py) until output is legible, then writes an HF-layout checkpoint
to fasttalk_tpu/assets/tinychat/ that serves through the standard path
(loader → config_from_hf → checkpoint chat template → EOS stop) with
zero code edits.

Usage:
    python scripts/train_tiny_chat.py [--steps 6000] [--out DIR]

Runs on whatever jax.devices() offers (TPU ~minutes; CPU slower). The
export is committed, so CI and demos never retrain.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from fasttalk_tpu.models.configs import ModelConfig  # noqa: E402
from fasttalk_tpu.models.llama import init_params  # noqa: E402
from fasttalk_tpu.parallel.sharding import shard_params  # noqa: E402
from fasttalk_tpu.training import (CHAT_TEMPLATE_JINJA, SPECIALS,  # noqa: E402
                                   corpus_texts, export_checkpoint,
                                   greedy_generate, make_eval_loss,
                                   make_sampled_train_step, pack_tokens,
                                   render, single_device_mesh,
                                   train_tokenizer)

TINYCHAT = ModelConfig(
    name="tinychat", vocab_size=2048, hidden_size=256,
    intermediate_size=768, num_layers=4, num_heads=8, num_kv_heads=4,
    head_dim=32, rope_theta=10000.0, rms_eps=1e-5, tie_embeddings=True,
    max_position=1024)

SEQ = 256
BATCH = 64


def build_data(tok, n_convs: int, seed: int) -> np.ndarray:
    stream: list[int] = []
    for text in corpus_texts(n_convs, seed=seed):
        stream.extend(tok.encode(text, add_special_tokens=False).ids)
    return pack_tokens(stream, SEQ)


def recall_probe(params, tok, eot: int) -> tuple[int, int, list[str]]:
    """Greedy name/color/pet recall over held-out conversations: the
    pass rate is the go/no-go for exporting."""
    probes = [
        ([{"role": "system",
           "content": "You are a helpful voice assistant. Keep "
                      "responses concise and conversational."},
          {"role": "user", "content": f"my name is {name}."},
          {"role": "assistant", "content": f"Nice to meet you, {name}!"},
          {"role": "user", "content": "what is my name?"}],
         name) for name in ("Alice", "Rex", "Marta", "Hugo")
    ] + [
        ([{"role": "user", "content": f"my favorite color is {c}."},
          {"role": "assistant", "content": f"{c.capitalize()} is a "
                                           "lovely color!"},
          {"role": "user", "content": "count from one to three."},
          {"role": "assistant", "content": "One, two, three."},
          {"role": "user", "content": "what is my favorite color?"}],
         c) for c in ("teal", "gold")
    ]
    ok, out = 0, []
    for msgs, expect in probes:
        ids = tok.encode(render(msgs, add_generation_prompt=True),
                         add_special_tokens=False).ids
        gen = greedy_generate(params, TINYCHAT, ids, max_new=24,
                              eos_id=eot)
        text = tok.decode(gen, skip_special_tokens=True)
        out.append(f"  {expect!r} -> {text!r}")
        if expect.lower() in text.lower():
            ok += 1
    return ok, len(probes), out


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6000)
    ap.add_argument("--convs", type=int, default=40000)
    ap.add_argument("--out", default=os.path.join(
        REPO, "fasttalk_tpu", "assets", "tinychat"))
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--force-export", action="store_true",
                    help="export even if the recall probe fails "
                         "(smoke-testing the pipeline only)")
    args = ap.parse_args()

    print(f"devices: {jax.devices()}", file=sys.stderr)
    os.makedirs(args.out, exist_ok=True)

    t0 = time.monotonic()
    texts = list(corpus_texts(args.convs, seed=args.seed))
    tok = train_tokenizer(texts, vocab_size=TINYCHAT.vocab_size,
                          specials=SPECIALS,
                          out_path=os.path.join(args.out,
                                                "tokenizer.json"))
    assert tok.get_vocab_size() <= TINYCHAT.vocab_size
    eot = tok.token_to_id("<|eot|>")
    data = build_data(tok, args.convs, args.seed)
    held = build_data(tok, 512, seed=args.seed + 1)[:BATCH]
    print(f"corpus: {args.convs} convs, {data.size:,} train tokens "
          f"({data.shape[0]} rows), vocab {tok.get_vocab_size()}, "
          f"{time.monotonic() - t0:.1f}s", file=sys.stderr)

    mesh = single_device_mesh()
    params = init_params(TINYCHAT, jax.random.PRNGKey(args.seed),
                         dtype=jnp.float32)
    params = shard_params(params, mesh)
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, 1e-3, warmup_steps=min(200, max(1, args.steps // 10)),
        decay_steps=args.steps, end_value=1e-4)
    optimizer = optax.adamw(schedule, weight_decay=0.01)
    opt_state = optimizer.init(params)  # zeros_like → inherits shardings

    step_fn = make_sampled_train_step(TINYCHAT, optimizer, mesh, BATCH)
    eval_fn = make_eval_loss(TINYCHAT)
    data_dev = jax.device_put(data)
    held_dev = jax.device_put(held)

    t0 = time.monotonic()
    loss = None
    for step in range(args.steps):
        params, opt_state, loss = step_fn(params, opt_state, data_dev,
                                          jnp.int32(step))
        if step % 500 == 0 or step == args.steps - 1:
            train_l = float(loss)
            held_l = float(eval_fn(params, held_dev))
            print(f"step {step:5d}  train {train_l:.4f}  "
                  f"held-out {held_l:.4f}  "
                  f"({time.monotonic() - t0:.0f}s)", file=sys.stderr)

    ok, total, lines = recall_probe(params, tok, eot)
    print(f"recall probe: {ok}/{total}", file=sys.stderr)
    for line in lines:
        print(line, file=sys.stderr)
    if ok < total and not args.force_export:
        print("RECALL PROBE FAILED — not exporting. Train longer.",
              file=sys.stderr)
        sys.exit(1)

    export_checkpoint(
        params, TINYCHAT, args.out,
        chat_template=CHAT_TEMPLATE_JINJA, eos_token="<|eot|>",
        bos_token="<|bos|>",
        tokenizer_json=os.path.join(args.out, "tokenizer.json"))
    print(f"exported {args.out}", file=sys.stderr)


if __name__ == "__main__":
    main()
