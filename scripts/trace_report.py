#!/usr/bin/env python
"""Offline per-phase latency report from a JSONL trace dump.

Input: the JSONL produced by ``GET /traces?format=jsonl`` (or
``/traces/{request_id}?format=jsonl``) on the monitoring port — one
span record per line (observability/export.py schema). Output: a
per-phase table of count / total / p50 / p95 / p99 span durations, the
thing a perf PR quotes before and after.

Usage:
    python scripts/trace_report.py dump.jsonl
    curl -s localhost:9092/traces?format=jsonl | \
        python scripts/trace_report.py -
    python scripts/trace_report.py --slo dump.jsonl      # CI gate
    python scripts/trace_report.py --journey dump.jsonl  # CI gate

``--journey`` aggregates the per-token hop waterfall from
``token_journey`` summary spans (serving/server.py emits one per
opted-in request; observability/journey.py defines the hops): per-hop
count / total / p50 / p95 / p99 across every recorded frame, plus a
reconciliation gate — each request's hop-sum must match its wall
clock within ``JOURNEY_TOL`` (default 0.10, i.e. |1 - sum/wall| ≤
10%) — and exits non-zero on violation, so a bench run can prove the
decomposition is honest, not just pretty.

``--slo`` evaluates the dump against the configured SLO targets
(``SLO_TTFT_P95_MS`` etc. — same knobs and defaults as
fasttalk_tpu/observability/slo.py) and exits non-zero on violation, so
a bench run can gate CI on its own trace dump. Derivations from span
records (the dump has no per-token data):

- TTFT per request: the ``first_token`` marker minus the request's
  submit time when present, else queue_wait + prefill durations (the
  prefill span ends at the first-token sample).
- queue wait: the ``queue_wait`` span duration.
- inter-token gap: estimated per ``decode_step`` span as
  ``dur_ms / tokens`` (the call's amortised per-token pace).
- error rate: requests whose ``decode`` span carries
  ``finish_reason: "error"`` or whose ``queue_wait`` is ``expired``.

Runs stdlib-only (no jax, no aiohttp import at module level) so it
works on a laptop against a dump scp'd from a TPU VM.
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from collections import defaultdict
from typing import Any, Iterable, TextIO

# Mirrors fasttalk_tpu/observability/slo.py DEFAULTS (this script must
# stay stdlib-only and importable on a bare laptop, so it cannot import
# the package); tests/test_slo.py pins the two tables equal.
SLO_DEFAULTS = {
    "SLO_TTFT_P95_MS": 1500.0,
    "SLO_INTER_TOKEN_P99_MS": 250.0,
    "SLO_QUEUE_WAIT_P95_MS": 1000.0,
    "SLO_ERROR_RATE": 0.01,
}


def load_records(fp: TextIO) -> list[dict[str, Any]]:
    """Parse JSONL span records (same validation as
    observability.export.load_jsonl, inlined to stay stdlib-only)."""
    records = []
    for i, line in enumerate(fp, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"line {i}: not valid JSON ({e})") from e
        if not isinstance(obj, dict) or "span" not in obj:
            raise ValueError(f"line {i}: not a span record")
        records.append(obj)
    return records


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile (matches utils.metrics.Histogram)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return sorted_vals[idx]


def phase_table(records: Iterable[dict[str, Any]]) -> list[dict[str, Any]]:
    """Aggregate span durations per phase name, sorted by total time."""
    by_phase: dict[str, list[float]] = defaultdict(list)
    for rec in records:
        by_phase[str(rec["span"])].append(float(rec.get("dur_ms", 0.0)))
    rows = []
    for name, durs in by_phase.items():
        durs.sort()
        rows.append({
            "phase": name,
            "count": len(durs),
            "total_ms": sum(durs),
            "p50_ms": percentile(durs, 50),
            "p95_ms": percentile(durs, 95),
            "p99_ms": percentile(durs, 99),
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def format_table(rows: list[dict[str, Any]]) -> str:
    headers = ("phase", "count", "total_ms", "p50_ms", "p95_ms", "p99_ms")
    cells = [[str(r["phase"]), str(r["count"]),
              f"{r['total_ms']:.1f}", f"{r['p50_ms']:.2f}",
              f"{r['p95_ms']:.2f}", f"{r['p99_ms']:.2f}"] for r in rows]
    widths = [max(len(h), *(len(c[i]) for c in cells)) if cells else len(h)
              for i, h in enumerate(headers)]
    def fmt(row: list[str]) -> str:
        return "  ".join(
            c.ljust(widths[i]) if i == 0 else c.rjust(widths[i])
            for i, c in enumerate(row))
    lines = [fmt(list(headers)), fmt(["-" * w for w in widths])]
    lines.extend(fmt(c) for c in cells)
    return "\n".join(lines)


def kv_phase_note(records: Iterable[dict[str, Any]]) -> str | None:
    """Host-KV offload tier percentiles (docs/KVCACHE.md): restore
    time sits between admission and first token, so the SLO gate's
    TTFT/queue-wait numbers already include it — this note makes the
    contribution visible next to the verdict. ``kv_restore`` spans are
    per-request; ``kv_offload`` records are process-level (parks run
    during other sessions' admissions)."""
    parts = []
    for name in ("kv_restore", "kv_offload"):
        durs = sorted(float(r.get("dur_ms", 0.0)) for r in records
                      if r.get("span") == name)
        if durs:
            parts.append(
                f"{name}: n={len(durs)} p50={percentile(durs, 50):.2f} "
                f"p95={percentile(durs, 95):.2f} "
                f"p99={percentile(durs, 99):.2f} ms")
    if not parts:
        return None
    return ("host-KV offload (counted inside queue-wait→first-token): "
            + "; ".join(parts))


# Offline mirror of observability/perf.py GAP_CAUSES; classification
# here comes from the dump's OWN evidence spans overlapping each gap
# (detok/ws/queue/radix-named spans), not the live host sampler.
GAP_CAUSES = ("detok", "ws_send", "scheduler", "radix", "gc", "other")


def _span_cause(name: str) -> str | None:
    """Which host-gap cause a non-engine span is evidence for."""
    n = name.lower()
    if "detok" in n:
        return "detok"
    if n.startswith("ws_") or "ws_send" in n or "ws_write" in n:
        return "ws_send"
    if "queue" in n or "sched" in n:
        return "scheduler"
    if "radix" in n:
        return "radix"
    if n == "gc" or n.startswith("gc_"):
        return "gc"
    return None


def perf_attribution(records: Iterable[dict[str, Any]],
                     idle_gap_ms: float | None = None,
                     peak_tflops: float | None = None,
                     ) -> dict[str, Any] | None:
    """Offline step-ledger attribution over a dump's process-level
    rows (``engine_step`` dispatch→retirement intervals,
    ``engine_prefill`` dispatch rows, and token-stat-free
    ``engine_op`` device calls) — the stdlib mirror of
    observability/perf.py's report, covering the dump's whole span:
    wall-time decomposition (device busy / host gap / idle via the
    PERF_IDLE_GAP_MS threshold), padding waste, occupancy, useful
    tok/s, MFU when the rows carry FLOP estimates and a roofline is
    configured (PERF_PEAK_TFLOPS), the per-program device-time table
    (rows stamped with their executable's ``program`` key), and the
    host-gap cause decomposition (gap overlap with the dump's own
    detok/ws/scheduler/radix evidence spans; the live /perf endpoint
    classifies the same gaps with the host stack sampler instead).
    None when the dump has no engine rows."""
    if idle_gap_ms is None:
        raw = os.environ.get("PERF_IDLE_GAP_MS", "").strip()
        try:
            idle_gap_ms = float(raw) if raw else 250.0
        except ValueError:
            idle_gap_ms = 250.0
    if peak_tflops is None:
        raw = os.environ.get("PERF_PEAK_TFLOPS", "").strip()
        try:
            peak_tflops = float(raw) if raw else 0.0
        except ValueError:
            peak_tflops = 0.0
    records = list(records)
    rows = [r for r in records
            if r.get("span") in ("engine_step", "engine_prefill",
                                 "engine_op")]
    if not rows:
        return None
    ivals = sorted((float(r["ts"]),
                    float(r["ts"]) + float(r.get("dur_ms", 0.0)) / 1e3)
                   for r in rows)
    start, end = ivals[0][0], max(b for _, b in ivals)
    merged: list[list[float]] = []
    for a, b in ivals:
        if merged and a <= merged[-1][1]:
            merged[-1][1] = max(merged[-1][1], b)
        else:
            merged.append([a, b])
    busy = sum(b - a for a, b in merged)
    thresh = idle_gap_ms / 1e3
    host_gap = idle = 0.0
    gap_ivals: list[tuple[float, float]] = []
    cursor = start
    for a, b in merged:
        g = a - cursor
        if g > 0:
            if g > thresh:
                idle += g
            else:
                host_gap += g
                gap_ivals.append((cursor, a))
        cursor = max(cursor, b)

    # Per-program device time: the same boundary-sweep the live ledger
    # uses — elementary segments split evenly among the programs in
    # flight, so concurrent dispatches never double-count and the
    # per-program seconds sum back to the busy union.
    events: list[tuple[float, int, str]] = []
    prog_calls: dict[str, int] = defaultdict(int)
    prog_tokens: dict[str, int] = defaultdict(int)
    for r in rows:
        a0 = float(r["ts"])
        b0 = a0 + float(r.get("dur_ms", 0.0)) / 1e3
        attrs0 = r.get("attrs") or {}
        prog = str(attrs0.get("program") or r["span"])
        prog_calls[prog] += 1
        prog_tokens[prog] += int(attrs0.get("tokens", 0) or 0)
        if b0 > a0:
            events.append((a0, 1, prog))
            events.append((b0, -1, prog))
    prog_busy: dict[str, float] = defaultdict(float)
    active: dict[str, int] = defaultdict(int)
    pts = sorted(events)
    prev_t: float | None = None
    i = 0
    while i < len(pts):
        t = pts[i][0]
        if prev_t is not None and active and t > prev_t:
            share = (t - prev_t) / sum(active.values())
            for p, n in active.items():
                prog_busy[p] += share * n
        while i < len(pts) and pts[i][0] == t:
            _, d, p = pts[i]
            active[p] += d
            if active[p] <= 0:
                del active[p]
            i += 1
        prev_t = t
    if prog_busy:
        busy = math.fsum(prog_busy.values())

    # Host-gap causes from overlap with the dump's evidence spans;
    # over-covering (overlapping evidence) is scaled back so the named
    # causes never exceed the gap they explain.
    causes = {c: 0.0 for c in GAP_CAUSES if c != "other"}
    cspans = []
    for r in records:
        c = _span_cause(str(r.get("span", "")))
        if c is not None:
            a0 = float(r.get("ts", 0.0))
            cspans.append((a0, a0 + float(r.get("dur_ms", 0.0)) / 1e3,
                           c))
    for ga, gb in gap_ivals:
        for a0, b0, c in cspans:
            ov = min(gb, b0) - max(ga, a0)
            if ov > 0:
                causes[c] += ov
    named = sum(causes.values())
    if named > host_gap > 0:
        scale = host_gap / named
        causes = {c: v * scale for c, v in causes.items()}
        named = host_gap
    causes["other"] = max(0.0, host_gap - named)
    window = end - start
    decode_toks = prefill_toks = computed = 0
    occ_w = occ_s = flops = kv_bytes = 0.0
    for r in rows:
        a = r.get("attrs") or {}
        flops += float(a.get("flops", 0.0))
        if r["span"] == "engine_step":
            decode_toks += int(a.get("tokens", 0))
            computed += int(a.get("rows", 0))
            kv_bytes += float(a.get("kv_bytes", 0.0))
            dur = float(r.get("dur_ms", 0.0))
            occ_w += dur
            occ_s += dur * float(a.get("occupancy", 0.0))
        elif r["span"] == "engine_prefill":
            prefill_toks += int(a.get("tokens", 0))
            computed += int(a.get("rows", a.get("tokens", 0)))
    useful = decode_toks + prefill_toks
    achieved = flops / window / 1e12 if window > 0 else 0.0
    return {
        "n_rows": len(rows),
        "window_s": window,
        "device_busy_frac": busy / window if window > 0 else None,
        "host_gap_frac": host_gap / window if window > 0 else None,
        "idle_frac": idle / window if window > 0 else None,
        "decode_tokens": decode_toks,
        "prefill_tokens": prefill_toks,
        "padding_waste_frac": 1.0 - useful / computed
        if computed > 0 else None,
        "useful_tok_s": useful / window if window > 0 else None,
        "occupancy_mean": occ_s / occ_w if occ_w > 0 else None,
        "achieved_tflops": achieved,
        "mfu": achieved / peak_tflops if peak_tflops > 0 else None,
        # KV attention-read bandwidth (engine rows carry honest
        # kv_bytes: int8+scales under KV_QUANT=int8, bf16 otherwise).
        "kv_read_gbps": kv_bytes / window / 1e9 if window > 0
        and kv_bytes else None,
        "programs": {
            "total_busy_s": busy,
            "by_program": sorted(
                ({"program": p, "busy_s": s,
                  "frac_of_busy": s / busy if busy > 0 else None,
                  "calls": prog_calls[p], "tokens": prog_tokens[p]}
                 for p, s in prog_busy.items()),
                key=lambda e: -e["busy_s"]),
        },
        "host_gap_causes": {
            "host_gap_s": host_gap,
            "by_cause": {c: {"s": v,
                             "frac": v / host_gap if host_gap > 0
                             else None}
                         for c, v in causes.items()},
        },
    }


def format_perf(p: dict[str, Any]) -> str:
    def pct(v: float | None) -> str:
        return "-" if v is None else f"{v:.1%}"

    def num(v: float | None, fmt: str = "{:.2f}") -> str:
        return "-" if v is None else fmt.format(v)

    lines = [
        f"perf attribution ({p['n_rows']} engine rows over "
        f"{p['window_s']:.2f}s)",
        f"  wall time: device busy {pct(p['device_busy_frac'])}  "
        f"host gap {pct(p['host_gap_frac'])}  "
        f"idle {pct(p['idle_frac'])}",
        f"  tokens: {p['decode_tokens']} decode + "
        f"{p['prefill_tokens']} prefill useful "
        f"({num(p['useful_tok_s'], '{:.1f}')} tok/s); "
        f"padding waste {pct(p['padding_waste_frac'])}; "
        f"occupancy {num(p['occupancy_mean'])}",
        f"  flops: {p['achieved_tflops']:.4f} TFLOP/s achieved"
        + ("" if p["mfu"] is None else f"; MFU {p['mfu']:.2%}"
           " (PERF_PEAK_TFLOPS roofline)")
        + ("" if p.get("kv_read_gbps") is None
           else f"; KV read {p['kv_read_gbps']:.3f} GB/s"),
    ]
    progs = (p.get("programs") or {}).get("by_program") or []
    if progs:
        lines.append(f"  per-program device time "
                     f"({p['programs']['total_busy_s']:.3f}s busy):")
        for e in progs[:12]:
            lines.append(
                f"    {e['busy_s']:8.3f}s {pct(e['frac_of_busy']):>6} "
                f"x{e['calls']:<5d} {e['program']}")
        if len(progs) > 12:
            lines.append(f"    ... and {len(progs) - 12} more")
    hg = p.get("host_gap_causes")
    if hg and hg.get("host_gap_s", 0.0) > 0:
        parts = [f"{c} {d['s'] * 1e3:.0f}ms ({pct(d['frac'])})"
                 for c, d in hg["by_cause"].items() if d["s"] > 0]
        lines.append(f"  host-gap causes ({hg['host_gap_s'] * 1e3:.0f}"
                     f"ms between device calls): " + "  ".join(parts))
    return "\n".join(lines)


# Mirrors observability/journey.py HOPS (stdlib-only: no package
# import); tests/test_fleet_trace.py pins the two tuples equal.
JOURNEY_HOPS = ("engine", "device_fetch", "detok_emit", "loop_dequeue",
                "ws_write")


def _journey_tol() -> float:
    raw = os.environ.get("JOURNEY_TOL", "").strip()
    try:
        tol = float(raw) if raw else 0.10
    except ValueError:
        tol = 0.10
    return tol


def journey_report(records: Iterable[dict[str, Any]],
                   tol: float | None = None,
                   ) -> tuple[list[dict[str, Any]],
                              list[dict[str, Any]], bool]:
    """Aggregate ``token_journey`` spans: (hop_rows, recon_rows, ok).

    hop_rows: per-hop percentile table pooled over every request's
    (capped) per-frame arrays. recon_rows: one row per request with
    its hop-sum vs wall-clock ratio, checked against ``tol`` —
    requests whose span carries no reconciliation (zero wall) pass
    vacuously. ok is False when any request's decomposition fails to
    reconcile."""
    if tol is None:
        tol = _journey_tol()
    by_hop: dict[str, list[float]] = defaultdict(list)
    recon_rows: list[dict[str, Any]] = []
    for rec in records:
        if rec.get("span") != "token_journey":
            continue
        attrs = rec.get("attrs") or {}
        frames_ms = attrs.get("frames_ms") or {}
        for hop, vals in frames_ms.items():
            if isinstance(vals, list):
                by_hop[str(hop)].extend(float(v) for v in vals)
        wall = float(attrs.get("wall_ms") or 0.0)
        hops_sum = float(attrs.get("hops_sum_ms") or 0.0)
        ratio = hops_sum / wall if wall > 0 else None
        recon_rows.append({
            "request_id": rec.get("request_id", "?"),
            "frames": attrs.get("frames"),
            "wall_ms": wall,
            "hops_sum_ms": hops_sum,
            "ratio": ratio,
            "ok": ratio is None or abs(1.0 - ratio) <= tol,
        })
    hop_rows: list[dict[str, Any]] = []
    for hop in JOURNEY_HOPS:
        vals = sorted(by_hop.pop(hop, []))
        hop_rows.append({
            "phase": hop, "count": len(vals), "total_ms": sum(vals),
            "p50_ms": percentile(vals, 50),
            "p95_ms": percentile(vals, 95),
            "p99_ms": percentile(vals, 99),
        })
    for hop, vals in sorted(by_hop.items()):  # unknown hops: show, last
        vals.sort()
        hop_rows.append({
            "phase": hop, "count": len(vals), "total_ms": sum(vals),
            "p50_ms": percentile(vals, 50),
            "p95_ms": percentile(vals, 95),
            "p99_ms": percentile(vals, 99),
        })
    ok = all(r["ok"] for r in recon_rows)
    return hop_rows, recon_rows, ok


def format_journey(hop_rows: list[dict[str, Any]],
                   recon_rows: list[dict[str, Any]],
                   tol: float) -> str:
    lines = ["token journey (per-frame hop decomposition)",
             format_table(hop_rows), ""]
    header = (f"{'request_id':<34}{'frames':>8}{'wall_ms':>12}"
              f"{'hop_sum':>12}{'ratio':>8}  result")
    lines.append(header)
    lines.append("-" * len(header))
    for r in recon_rows:
        ratio = "-" if r["ratio"] is None else f"{r['ratio']:.3f}"
        frames = "-" if r["frames"] is None else str(r["frames"])
        lines.append(
            f"{str(r['request_id'])[:33]:<34}{frames:>8}"
            f"{r['wall_ms']:>12.1f}{r['hops_sum_ms']:>12.1f}"
            f"{ratio:>8}  " + ("PASS" if r["ok"] else "FAIL"))
    lines.append(f"(reconciliation tolerance ±{tol:.0%}, JOURNEY_TOL)")
    return "\n".join(lines)


def _slo_target(name: str) -> float:
    raw = os.environ.get(name, "").strip()
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return SLO_DEFAULTS[name]


def slo_evaluate(records: Iterable[dict[str, Any]],
                 ) -> tuple[list[dict[str, Any]], bool]:
    """Evaluate a dump against the SLO targets. Returns (rows, ok);
    an objective with no evaluable data passes vacuously (n=0)."""
    by_req: dict[str, list[dict[str, Any]]] = defaultdict(list)
    for rec in records:
        rid = rec.get("request_id")
        if rid:
            by_req[rid].append(rec)
    ttfts: list[float] = []
    waits: list[float] = []
    gaps: list[float] = []
    errors = 0
    shed = 0
    for rid, spans in by_req.items():
        named: dict[str, dict[str, Any]] = {}
        for s in spans:
            named.setdefault(str(s["span"]), s)
        qw = named.get("queue_wait")
        if ((qw or {}).get("attrs") or {}).get("expired"):
            # Queue-deadline expiry is load SHEDDING: the live SLO
            # engine records it as a shed, not a sample (engine._finish
            # / slo.record_shed) — the CI gate must agree, or an
            # overload bench that /slo calls healthy would fail here.
            shed += 1
            continue
        if qw is not None:
            waits.append(float(qw.get("dur_ms", 0.0)))
        first = named.get("first_token")
        if first is not None:
            submit = min(float(s["ts"]) for s in spans)
            ttfts.append((float(first["ts"]) - submit) * 1000.0)
        elif qw is not None and "prefill" in named:
            ttfts.append(float(qw.get("dur_ms", 0.0))
                         + float(named["prefill"].get("dur_ms", 0.0)))
        for s in spans:
            if s["span"] == "decode_step":
                toks = (s.get("attrs") or {}).get("tokens") or 0
                if toks > 0:
                    gaps.append(float(s.get("dur_ms", 0.0)) / toks)
        reason = (named.get("decode", {}).get("attrs") or {}) \
            .get("finish_reason")
        if reason == "error":
            errors += 1

    rows: list[dict[str, Any]] = []

    def check(objective: str, values: list[float], q: float,
              target: float, unit: str = "ms") -> None:
        values = sorted(values)
        observed = percentile(values, q) if values else None
        rows.append({
            "objective": objective, "n": len(values),
            "observed": observed, "target": target, "unit": unit,
            "ok": observed is None or observed <= target,
        })

    check("ttft_p95_ms", ttfts, 95, _slo_target("SLO_TTFT_P95_MS"))
    check("inter_token_p99_ms", gaps, 99,
          _slo_target("SLO_INTER_TOKEN_P99_MS"))
    check("queue_wait_p95_ms", waits, 95,
          _slo_target("SLO_QUEUE_WAIT_P95_MS"))
    n_req = len(by_req) - shed  # sheds are not SLO samples
    err_rate = errors / n_req if n_req > 0 else None
    rows.append({
        "objective": "error_rate", "n": max(0, n_req),
        "observed": err_rate,
        "target": _slo_target("SLO_ERROR_RATE"), "unit": "frac",
        "ok": err_rate is None
        or err_rate <= _slo_target("SLO_ERROR_RATE"),
    })
    if shed:
        print(f"note: {shed} deadline-expired request(s) excluded "
              "(shed, not SLO samples)", file=sys.stderr)
    return rows, all(r["ok"] for r in rows)


def format_slo_table(rows: list[dict[str, Any]]) -> str:
    lines = [f"{'objective':<22}{'n':>6}{'observed':>12}{'target':>12}"
             f"  result"]
    lines.append("-" * len(lines[0]))
    for r in rows:
        obs = "-" if r["observed"] is None else f"{r['observed']:.2f}"
        lines.append(
            f"{r['objective']:<22}{r['n']:>6}{obs:>12}"
            f"{r['target']:>12.2f}  "
            + ("PASS" if r["ok"] else "FAIL"))
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="JSONL trace dump path, or - for stdin")
    ap.add_argument("--slo", action="store_true",
                    help="evaluate the dump against the configured "
                    "SLO_* targets; exit 1 on violation (CI gate)")
    ap.add_argument("--perf", action="store_true",
                    help="append the step-ledger attribution section "
                    "(wall-time decomposition, padding waste, "
                    "occupancy, MFU) computed from the dump's "
                    "engine_step/engine_prefill rows")
    ap.add_argument("--journey", action="store_true",
                    help="per-token hop waterfall from token_journey "
                    "spans + hop-sum/wall-clock reconciliation gate "
                    "(JOURNEY_TOL, default 10%%); exit 1 on violation")
    args = ap.parse_args(argv)
    try:
        if args.dump == "-":
            records = load_records(sys.stdin)
        else:
            with open(args.dump, encoding="utf-8") as fp:
                records = load_records(fp)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not records:
        print("error: no span records in dump", file=sys.stderr)
        return 1
    requests = {r["request_id"] for r in records
                if r.get("request_id")}
    print(f"{len(records)} spans across {len(requests)} requests")
    print()
    kv_note = kv_phase_note(records)
    perf = perf_attribution(records) if args.perf else None
    if args.journey:
        tol = _journey_tol()
        hop_rows, recon_rows, ok = journey_report(records, tol)
        if not recon_rows:
            print("error: no token_journey spans in dump (opt in with "
                  "journey:true in the session config, or "
                  "client.py --journey)", file=sys.stderr)
            return 1
        print(format_journey(hop_rows, recon_rows, tol))
        if not ok:
            print("\nJOURNEY RECONCILIATION VIOLATION", file=sys.stderr)
            return 1
        print("\nall journeys reconcile with wall clock")
        return 0
    if args.slo:
        rows, ok = slo_evaluate(records)
        print(format_slo_table(rows))
        if kv_note:
            print(f"\n{kv_note}")
        if perf is not None:
            print(f"\n{format_perf(perf)}")
        if not ok:
            print("\nSLO VIOLATION", file=sys.stderr)
            return 1
        print("\nall SLO targets met")
        return 0
    print(format_table(phase_table(records)))
    if kv_note:
        print(f"\n{kv_note}")
    if args.perf:
        if perf is None:
            print("\nperf attribution: no engine_step/engine_prefill "
                  "rows in dump")
        else:
            print(f"\n{format_perf(perf)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
