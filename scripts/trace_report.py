#!/usr/bin/env python
"""Offline per-phase latency report from a JSONL trace dump.

Input: the JSONL produced by ``GET /traces?format=jsonl`` (or
``/traces/{request_id}?format=jsonl``) on the monitoring port — one
span record per line (observability/export.py schema). Output: a
per-phase table of count / total / p50 / p95 / p99 span durations, the
thing a perf PR quotes before and after.

Usage:
    python scripts/trace_report.py dump.jsonl
    curl -s localhost:9092/traces?format=jsonl | \
        python scripts/trace_report.py -

Runs stdlib-only (no jax, no aiohttp import at module level) so it
works on a laptop against a dump scp'd from a TPU VM.
"""

from __future__ import annotations

import argparse
import json
import math
import sys
from collections import defaultdict
from typing import Any, Iterable, TextIO


def load_records(fp: TextIO) -> list[dict[str, Any]]:
    """Parse JSONL span records (same validation as
    observability.export.load_jsonl, inlined to stay stdlib-only)."""
    records = []
    for i, line in enumerate(fp, start=1):
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"line {i}: not valid JSON ({e})") from e
        if not isinstance(obj, dict) or "span" not in obj:
            raise ValueError(f"line {i}: not a span record")
        records.append(obj)
    return records


def percentile(sorted_vals: list[float], q: float) -> float:
    """Nearest-rank percentile (matches utils.metrics.Histogram)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, math.ceil(q / 100.0 * len(sorted_vals)) - 1))
    return sorted_vals[idx]


def phase_table(records: Iterable[dict[str, Any]]) -> list[dict[str, Any]]:
    """Aggregate span durations per phase name, sorted by total time."""
    by_phase: dict[str, list[float]] = defaultdict(list)
    for rec in records:
        by_phase[str(rec["span"])].append(float(rec.get("dur_ms", 0.0)))
    rows = []
    for name, durs in by_phase.items():
        durs.sort()
        rows.append({
            "phase": name,
            "count": len(durs),
            "total_ms": sum(durs),
            "p50_ms": percentile(durs, 50),
            "p95_ms": percentile(durs, 95),
            "p99_ms": percentile(durs, 99),
        })
    rows.sort(key=lambda r: -r["total_ms"])
    return rows


def format_table(rows: list[dict[str, Any]]) -> str:
    headers = ("phase", "count", "total_ms", "p50_ms", "p95_ms", "p99_ms")
    cells = [[str(r["phase"]), str(r["count"]),
              f"{r['total_ms']:.1f}", f"{r['p50_ms']:.2f}",
              f"{r['p95_ms']:.2f}", f"{r['p99_ms']:.2f}"] for r in rows]
    widths = [max(len(h), *(len(c[i]) for c in cells)) if cells else len(h)
              for i, h in enumerate(headers)]
    def fmt(row: list[str]) -> str:
        return "  ".join(
            c.ljust(widths[i]) if i == 0 else c.rjust(widths[i])
            for i, c in enumerate(row))
    lines = [fmt(list(headers)), fmt(["-" * w for w in widths])]
    lines.extend(fmt(c) for c in cells)
    return "\n".join(lines)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("dump", help="JSONL trace dump path, or - for stdin")
    args = ap.parse_args(argv)
    try:
        if args.dump == "-":
            records = load_records(sys.stdin)
        else:
            with open(args.dump, encoding="utf-8") as fp:
                records = load_records(fp)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not records:
        print("error: no span records in dump", file=sys.stderr)
        return 1
    requests = {r["request_id"] for r in records
                if r.get("request_id")}
    print(f"{len(records)} spans across {len(requests)} requests")
    print()
    print(format_table(phase_table(records)))
    return 0


if __name__ == "__main__":
    sys.exit(main())
