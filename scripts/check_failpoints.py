#!/usr/bin/env python3
"""Failpoint coverage lint (docs/RESILIENCE.md, run_tests.sh --chaos).

Statically cross-checks three surfaces — no imports, pure AST/text, so
it runs in milliseconds anywhere:

1. The CATALOG in fasttalk_tpu/resilience/failpoints.py is the single
   source of truth for failpoint names.
2. Every catalog name is FIRED by at least one call site under
   fasttalk_tpu/ (a registered-but-never-fired point is dead weight),
   and every fire("...") literal uses a catalog name (a typo'd name
   would assert at runtime — catch it here first).
3. Every catalog name is INJECTED by at least one chaos test in
   tests/test_chaos.py or tests/test_fleet_fabric.py (a failpoint no
   chaos test exercises is an unproven recovery path — the exact gap
   this lint closes), and no test references a nonexistent point.

Exit 0 = clean; exit 1 = problems, each printed on its own line.
"""

from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
FAILPOINTS = REPO / "fasttalk_tpu" / "resilience" / "failpoints.py"
# Every file here is scanned for injections; a catalog point must be
# exercised by at least one of them (router seams live in the fleet
# fabric suite, everything else in the original chaos suite).
CHAOS_TESTS = (REPO / "tests" / "test_chaos.py",
               REPO / "tests" / "test_fleet_fabric.py",
               REPO / "tests" / "test_disagg.py")


def catalog_names() -> set[str]:
    """CATALOG keys, read from the AST (no import: the lint must not
    depend on the package's import-time env behaviour)."""
    tree = ast.parse(FAILPOINTS.read_text())
    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign) or isinstance(node, ast.Assign):
            targets = ([node.target] if isinstance(node, ast.AnnAssign)
                       else node.targets)
            names = [t.id for t in targets if isinstance(t, ast.Name)]
            if "CATALOG" in names and isinstance(node.value, ast.Dict):
                return {k.value for k in node.value.keys
                        if isinstance(k, ast.Constant)}
    raise SystemExit(f"{FAILPOINTS}: CATALOG dict literal not found")


def fire_call_sites() -> dict[str, list[str]]:
    """point name -> files under fasttalk_tpu/ that fire()/
    fire_async() it with a string literal first argument."""
    sites: dict[str, list[str]] = {}
    for path in sorted((REPO / "fasttalk_tpu").rglob("*.py")):
        if path == FAILPOINTS:
            continue  # the registry's own docstring examples
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError as e:  # pragma: no cover
            print(f"PROBLEM: {path}: unparseable ({e})")
            sys.exit(1)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            is_fire = (isinstance(func, ast.Attribute)
                       and func.attr in ("fire", "fire_async")) or (
                isinstance(func, ast.Name)
                and func.id in ("fire", "fire_async"))
            if not is_fire or not node.args:
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value,
                                                            str):
                sites.setdefault(arg.value, []).append(
                    str(path.relative_to(REPO)))
    return sites


def chaos_test_refs(names: set[str]) -> tuple[set[str], set[str]]:
    """(catalog names referenced in the chaos test files, point-shaped
    strings referenced that are NOT in the catalog). Points appear in
    spec strings ("point=action") and fire() calls, so a plain string
    scan over dotted names is the robust form."""
    text = "\n".join(p.read_text() for p in CHAOS_TESTS if p.exists())
    referenced = {n for n in names if n in text}
    # Any dotted token that appears on the left of '=<action>' in a
    # spec literal must be a real point.
    unknown = set()
    for m in re.finditer(
            r"[\"'\s,]([a-z_]+(?:\.[a-z_]+)+)=(?:error|hang|corrupt|"
            r"crash_thread|delay_ms)", text):
        if m.group(1) not in names:
            unknown.add(m.group(1))
    return referenced, unknown


def main() -> int:
    names = catalog_names()
    problems: list[str] = []

    sites = fire_call_sites()
    for name in sorted(names):
        if name not in sites:
            problems.append(
                f"catalog point {name!r} is never fired by any call "
                "site under fasttalk_tpu/")
    for name in sorted(set(sites) - names):
        problems.append(
            f"fire({name!r}) in {', '.join(sites[name])} is not in "
            "the failpoints CATALOG")

    missing = [p for p in CHAOS_TESTS if not p.exists()]
    if missing:
        problems.extend(f"{p} does not exist" for p in missing)
    else:
        referenced, unknown = chaos_test_refs(names)
        chaos_names = ", ".join(str(p.relative_to(REPO))
                                for p in CHAOS_TESTS)
        for name in sorted(names - referenced):
            problems.append(
                f"catalog point {name!r} is not injected by any test "
                f"in {chaos_names} (unproven recovery path)")
        for name in sorted(unknown):
            problems.append(
                f"chaos tests inject nonexistent point {name!r}")

    if problems:
        for p in problems:
            print(f"PROBLEM: {p}")
        return 1
    print(f"check_failpoints: {len(names)} catalog points, all fired "
          "in-tree and all injected by the chaos suites "
          f"({', '.join(str(p.name) for p in CHAOS_TESTS)})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
