"""Offline int4 checkpoint quantization (AWQ-calibrated or data-free).

Quantizes a float checkpoint into the WEIGHT_QUANT=int4 format and
writes it into the SAME prepared-weight cache the factory load path
reads (models/prepared_cache.py) — a server started afterwards with
WEIGHT_QUANT=int4 restores the calibrated leaves instead of re-doing
the data-free quantization, with zero serving-path changes. A manifest
JSON (chosen alpha/clip per layer, calibration provenance) lands next
to the cache for auditability.

Usage:
  python scripts/quantize_checkpoint.py --model tinychat \
      --model-path fasttalk_tpu/assets \
      [--group 128] [--calib corpus|/path/to/texts.txt] \
      [--calib-samples 16] [--seq-len 256] [--dtype bfloat16] \
      [--data-free] [--seed 0]

``--calib corpus`` (default) calibrates on rendered tinychat training
conversations (training/corpus.py); a file path uses its non-empty
lines. ``--data-free`` skips calibration entirely (int4.py fallback —
same scales the factory computes inline).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    ap = argparse.ArgumentParser(
        description="Quantize a checkpoint to the int4 tier "
                    "(AWQ-calibrated scale search by default)")
    ap.add_argument("--model", default="tinychat",
                    help="model config name (models/configs.py)")
    ap.add_argument("--model-path", default="fasttalk_tpu/assets",
                    help="MODEL_PATH the server will use")
    ap.add_argument("--group", type=int, default=128,
                    help="WEIGHT_QUANT_GROUP the server will use")
    ap.add_argument("--calib", default="corpus",
                    help="'corpus' or a UTF-8 text file of documents")
    ap.add_argument("--calib-samples", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--dtype", default="bfloat16",
                    choices=("bfloat16", "float32", "float16"),
                    help="serving dtype the cache is keyed by")
    ap.add_argument("--data-free", action="store_true",
                    help="skip AWQ; plain group-wise maxabs scales")
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax.numpy as jnp

    from fasttalk_tpu.engine.tokenizer import load_tokenizer
    from fasttalk_tpu.models.configs import get_model_config
    from fasttalk_tpu.models.loader import find_checkpoint_dir, load_params
    from fasttalk_tpu.models.prepared_cache import cache_meta, save_prepared
    from fasttalk_tpu.quantization.int4 import (quantize_params_int4,
                                                validate_group)

    model_cfg = get_model_config(args.model, args.model_path)
    validate_group(model_cfg, args.group)
    ckpt = find_checkpoint_dir(args.model_path, model_cfg.name)
    if not ckpt:
        print(f"error: no checkpoint for {model_cfg.name!r} under "
              f"{args.model_path!r}", file=sys.stderr)
        return 2
    dtype = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
             "float16": jnp.float16}[args.dtype]
    # Float32 host-side load: the scale search wants full-precision
    # stats; serving-dtype casting happens where it always does (the
    # non-quantized leaves are cast by the put hook below).
    import jax

    params = load_params(
        model_cfg, ckpt, dtype,
        put=lambda arr, path: jax.device_put(jnp.asarray(arr, jnp.float32)))

    manifest: dict = {"mode": "data-free", "group": int(args.group),
                      "model": model_cfg.name}
    if args.data_free:
        qparams = quantize_params_int4(params, args.group)
    else:
        from fasttalk_tpu.quantization.awq import (calibration_tokens,
                                                   quantize_params_awq)

        tokenizer = load_tokenizer(args.model_path, args.model,
                                   template=model_cfg.chat_template)
        tokens = calibration_tokens(
            tokenizer, n_samples=args.calib_samples,
            seq_len=args.seq_len, seed=args.seed, source=args.calib)
        print(f"calibrating on {tokens.shape[0]} x {tokens.shape[1]} "
              f"tokens from {args.calib!r}")
        qparams, awq_info = quantize_params_awq(params, model_cfg,
                                                tokens, args.group)
        manifest = {"mode": "awq", "model": model_cfg.name,
                    "calib": args.calib,
                    "calib_samples": int(tokens.shape[0]),
                    "seq_len": int(tokens.shape[1]),
                    "seed": args.seed, **awq_info}

    # Non-quantized leaves (norms, biases) must land in the SERVING
    # dtype or the cache's restore target (abstract_params) mismatches;
    # the quantization scales ("s") stay float32 BY FORMAT.
    def cast_plain(path, leaf):
        name = str(getattr(path[-1], "key", path[-1]))
        if name != "s" and hasattr(leaf, "dtype") \
                and leaf.dtype == jnp.float32:
            return leaf.astype(dtype)
        return leaf

    qparams = jax.tree_util.tree_map_with_path(cast_plain, qparams)
    meta = cache_meta(model_cfg, dtype, "int4", None, ckpt_dir=ckpt,
                      group=args.group)
    path = save_prepared(qparams, args.model_path, meta, block=True)
    if path is None:
        print("error: prepared-cache write failed", file=sys.stderr)
        return 1
    man_path = os.path.join(path, "quantize_manifest.json")
    with open(man_path, "w") as f:
        json.dump(manifest, f, indent=2)
    print(f"int4 prepared cache written: {path}")
    print(f"manifest: {man_path}")
    print(f"serve with: WEIGHT_QUANT=int4 WEIGHT_QUANT_GROUP="
          f"{args.group} MODEL_NAME={args.model} "
          f"MODEL_PATH={args.model_path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
