"""Churn soak: hammer the real WS server with adversarial session
behavior and verify nothing wedges, leaks, or crashes.

Mix per client, repeatedly: connect → start_session (sometimes with a
shared persona, sometimes unique) → user_message (short or long) → then
one of: consume fully / cancel mid-stream / abort the TCP transport
mid-stream / update_config mid-session / end_session cleanly. At the
end: zero client-observed errors, zero ERROR/CRITICAL log records,
/health healthy, engine queues drained (with a settle window for
in-flight cleanup), and a clean request still serves end to end.

Two profiles:

- ``device`` (default): the client mix is sized for a real chip. On
  the slow CPU backend this offered load saturates every slot, the
  circuit breaker opens — correctly — and the no-backoff clients tally
  its rejections as errors, so it cannot run in CI.
- ``ci`` (VERDICT r4 #7): slots-and-rate-scaled for the CPU backend —
  fewer clients, tiny budgets, the committed tinychat checkpoint
  (fast on CPU), short prompts. The same churn behaviors (cancel,
  TCP abort, config updates, clean ends) and the same zero-error
  invariants, runnable every round via tests/test_soak_ci.py instead
  of once per hardware session.

Usage: python scripts/soak.py [seconds] [ci|device]
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PORT = int(os.environ.get("BENCH_PORT", "18663"))
DURATION = float(sys.argv[1]) if len(sys.argv) > 1 else 120.0
PROFILE = (sys.argv[2] if len(sys.argv) > 2
           else os.environ.get("SOAK_PROFILE", "device"))
CI = PROFILE == "ci"
CLIENTS = 4 if CI else 12
MAX_TOKENS_CHOICES = [2, 4, 8] if CI else [4, 16, 48, 96]
LONG_FACTORS = [1, 1, 1, 4] if CI else [1, 1, 1, 40]
PERSONA = ("You are a terse ops assistant. Answer in one sentence. "
           * (4 if CI else 30))

STATS = {"completed": 0, "cancelled": 0, "aborted": 0, "closed": 0,
         "errors": 0, "config_updates": 0, "tokens": 0}


class _ErrorCounter(logging.Handler):
    def __init__(self):
        super().__init__(level=logging.ERROR)
        self.records: list[str] = []

    def emit(self, record):
        msg = record.getMessage()
        if "was not found in jax.local_devices" in msg:
            # Known jax/orbax-internal noise, not a framework failure:
            # a persistent-cache entry written under a different device
            # topology logs this ERROR and falls back to a fresh
            # compile/load. Matched on the message (not the logger) so
            # every OTHER jax-side ERROR still fails the soak.
            return
        self.records.append(msg)


def _abort_transport(ws) -> None:
    """Kill the TCP transport without a close handshake — a genuinely
    abrupt disconnect (raising out of `async with ws_connect` performs
    a GRACEFUL close in __aexit__, which is a different server path).
    Reaches into aiohttp internals; falls back to a plain close."""
    try:
        ws._response.connection.transport.abort()
    except Exception:
        pass


async def client_loop(http, cid: int, deadline: float) -> None:
    rng = random.Random(cid)
    while time.monotonic() < deadline:
        try:
            async with http.ws_connect(
                    f"ws://127.0.0.1:{PORT}/ws/llm",
                    heartbeat=30) as ws:
                msg = json.loads((await ws.receive()).data)
                assert msg["type"] == "session_started", msg
                cfg = {"max_tokens": rng.choice(MAX_TOKENS_CHOICES),
                       "temperature": rng.choice([0.0, 0.7, 1.2])}
                if rng.random() < 0.5:
                    cfg["system_prompt"] = PERSONA
                await ws.send_json({"type": "start_session",
                                    "config": cfg})
                await ws.receive()  # session_configured
                for _turn in range(rng.randint(1, 3)):
                    if time.monotonic() >= deadline:
                        break
                    text = ("tell me everything about everything " *
                            rng.choice(LONG_FACTORS))
                    await ws.send_json({"type": "user_message",
                                        "text": f"[{cid}] {text}"})
                    fate = rng.random()
                    tokens = 0
                    while True:
                        frame = await asyncio.wait_for(ws.receive(),
                                                       timeout=120)
                        if frame.type.name in ("CLOSE", "CLOSING",
                                               "CLOSED", "ERROR"):
                            STATS["closed"] += 1
                            raise ConnectionResetError
                        m = json.loads(frame.data)
                        if m["type"] == "token":
                            tokens += 1
                            STATS["tokens"] += 1
                            if fate < 0.2 and tokens >= 2:
                                await ws.send_json({"type": "cancel"})
                                fate = 1.0  # only cancel once
                            elif fate < 0.3 and tokens >= 2:
                                STATS["aborted"] += 1
                                _abort_transport(ws)
                                raise ConnectionResetError
                        elif m["type"] == "response_complete":
                            if m["stats"].get("finish_reason") == \
                                    "cancelled":
                                STATS["cancelled"] += 1
                            else:
                                STATS["completed"] += 1
                            break
                        elif m["type"] == "cancelled":
                            pass  # ack frame; completion still follows
                        elif m["type"] == "error":
                            STATS["errors"] += 1
                            break
                    if rng.random() < 0.2:
                        await ws.send_json({
                            "type": "update_config",
                            "config": {"temperature": 0.5}})
                        await ws.receive()  # config_updated
                        STATS["config_updates"] += 1
                if rng.random() < 0.7:
                    await ws.send_json({"type": "end_session"})
                    await asyncio.wait_for(ws.receive(), timeout=30)
        except (ConnectionResetError, asyncio.TimeoutError):
            continue
        except Exception as e:  # noqa: BLE001 — tally, keep soaking
            STATS["errors"] += 1
            print(f"client {cid}: {type(e).__name__}: {e}",
                  file=sys.stderr)


async def main() -> None:
    import aiohttp

    from fasttalk_tpu.serving.local import start_local_server
    from fasttalk_tpu.utils.config import Config

    errors = _ErrorCounter()
    logging.getLogger().addHandler(errors)

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    if CI:
        cfg = Config(llm_provider="tpu", model_name="tinychat",
                     model_path=os.path.join(repo, "fasttalk_tpu",
                                             "assets"),
                     decode_slots=8, max_model_len=1024,
                     default_context_window=1024, port=PORT,
                     monitoring_port=PORT + 1, quantize="none")
    else:
        cfg = Config(llm_provider="tpu",
                     model_name=os.environ.get("LLM_MODEL",
                                               "llama3.2:1b"),
                     decode_slots=16, max_model_len=2048,
                     default_context_window=2048, port=PORT,
                     monitoring_port=PORT + 1,
                     quantize=os.environ.get("TPU_QUANTIZE", "int8"))
    engine, runner = await start_local_server(cfg, warmup="fast")
    print(f"soaking {DURATION:.0f}s ({PROFILE} profile) with "
          f"{CLIENTS} churning clients...", file=sys.stderr)
    deadline = time.monotonic() + DURATION
    try:
        async with aiohttp.ClientSession() as http:
            await asyncio.gather(*(client_loop(http, i, deadline)
                                   for i in range(CLIENTS)))
            # Post-churn invariants. Cleanup of vanished clients is
            # asynchronous (server finally blocks + engine command
            # queue), so give the queues a settle window.
            for _ in range(40):
                async with http.get(
                        f"http://127.0.0.1:{PORT}/stats") as r:
                    stats = await r.json()
                if stats["engine"].get("waiting", 0) == 0 and \
                        stats["engine"].get("running", 0) == 0:
                    break
                await asyncio.sleep(0.5)
            else:
                raise AssertionError(
                    f"engine queues never drained: {stats['engine']}")
            async with http.get(
                    f"http://127.0.0.1:{PORT}/health") as r:
                health = await r.json()
            assert health["status"] == "healthy", health
            # A clean request still serves end to end.
            async with http.ws_connect(
                    f"ws://127.0.0.1:{PORT}/ws/llm") as ws:
                await ws.receive()
                # Greedy: at temperature the ci profile's trained
                # model can legally sample EOS first (zero text
                # tokens); greedy "hello" deterministically answers —
                # and still fails loudly on real post-churn corruption.
                await ws.send_json({"type": "start_session",
                                    "config": {"max_tokens": 8,
                                               "temperature": 0.0,
                                               "top_k": 0,
                                               "top_p": 1.0}})
                await ws.receive()
                # "hello" is in-distribution for the ci profile's
                # trained tinychat (an OOD prompt can legally answer
                # with an immediate EOS and zero text tokens).
                await ws.send_json({"type": "user_message",
                                    "text": "hello"})
                got_tokens = 0
                while True:
                    m = json.loads((await asyncio.wait_for(
                        ws.receive(), timeout=60)).data)
                    if m["type"] == "token":
                        got_tokens += 1
                    elif m["type"] == "response_complete":
                        break
                assert got_tokens > 0
    finally:
        await runner.cleanup()
        engine.shutdown()
    assert STATS["completed"] > 0, STATS
    assert STATS["errors"] == 0, STATS
    assert not errors.records, errors.records[:5]
    print(f"SOAK OK: {json.dumps(STATS)}")


if __name__ == "__main__":
    asyncio.run(main())
    # Every invariant has passed and the verdict is printed. Exit hard:
    # library atexit hooks (orbax async writer, tensorstore) have been
    # observed turning an already-passed soak into a flaky nonzero exit
    # during interpreter teardown.
    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(0)
