"""Split the prefill->first-token device time: relay RTT, prefill call
wall time per (bucket, group), decode-call wall time, fetch latency.

The TTFT profiler (scripts/profile_ttft.py) shows ~all of WS TTFT is
prefill_dispatch -> first_ready; this isolates what that chunk is made
of on the real device.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from fasttalk_tpu.engine.factory import build_engine
from fasttalk_tpu.observability.perf import PerfLedger, program_key
from fasttalk_tpu.observability.trace import Tracer
from fasttalk_tpu.utils.config import Config

REPS = 10

# Standalone step ledger (same fold as profile_decode.py): timed loops
# stamped with a program key land in a PerfLedger, so the script ends
# with the per-program attribution table GET /perf serves live.
_TRACER = Tracer(enabled=True)
_LEDGER = PerfLedger(tracer=_TRACER, window_s=3600.0)


def timed(label, fn, reps=REPS, program=None, **pattrs):
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        ts.append((time.perf_counter() - t0) * 1000)
    if program is not None:
        prog = program_key(program, **pattrs)
        end = time.monotonic()
        dt = float(np.median(ts)) / 1e3
        for i in range(reps):
            t1 = end - (reps - 1 - i) * dt
            _TRACER.step("engine_op", t1 - dt, t1, kind=program,
                         program=prog)
    print(f"  {label:44s} p50 {float(np.median(ts)):8.2f} ms  "
          f"min {min(ts):8.2f}  max {max(ts):8.2f}")
    return float(np.median(ts))


def print_programs() -> None:
    progs = (_LEDGER.report().get("programs") or {})
    rows = progs.get("by_program") or []
    if not rows:
        return
    print("== per-program device time (observability/perf.py "
          "ledger) ==", flush=True)
    for e in rows:
        print(f"  {e['busy_s']:8.3f}s {e['frac_of_busy']:7.1%} "
              f"x{e['calls']:<4d} {e['program']}")
    print(f"  {progs['total_busy_s']:8.3f}s total device busy "
          f"(per-program seconds sum to this by construction)")


def main() -> None:
    print(f"devices: {jax.devices()}")
    one = jnp.ones((), jnp.float32)
    timed("tiny-op dispatch+fetch (relay RTT)",
          lambda: np.asarray(one + 1.0))

    cfg = Config(llm_provider="tpu", model_name="llama3.2:1b",
                 decode_slots=16, max_model_len=2048,
                 default_context_window=2048, prefill_chunk=512,
                 dtype="bfloat16", enable_agent=False, quantize="int8")
    engine = build_engine(cfg)
    engine.warmup("fast")

    S = engine.num_slots
    inactive = engine._put(np.zeros((S,), bool))

    def decode_call(steps):
        fn = engine._get_decode_fn(512, steps)
        (engine.cache, engine._counts_dev, toks, engine._cur_tokens,
         engine._positions_dev, engine._rng_dev) = fn(
            engine.params, engine.cache, engine._counts_dev,
            engine._cur_tokens, engine._positions_dev, inactive,
            engine._temps_dev, engine._topks_dev, engine._topps_dev,
            engine._reps_dev, engine._press_dev, engine._freqs_dev,
            engine._rng_dev)
        return toks

    def prefill_call(bucket, gp, fetch):
        ctx = 512
        fn = engine._get_batched_prefill_fn(bucket, gp, ctx)
        rowcfg = np.zeros((gp, 7), np.float32)
        rowcfg[:, 0] = np.arange(S, S + gp)
        rowcfg[:, 4:] = (1.0, 40, 0.9)
        (engine.cache, firsts, engine._cur_tokens, engine._rng_dev) = fn(
            engine.params, engine.cache,
            np.zeros((gp, bucket), np.int32), rowcfg,
            engine._cur_tokens, engine._rng_dev)
        if fetch:
            np.asarray(firsts)
        return firsts

    # Warm the exact shapes used below.
    for gp in (1, S):
        np.asarray(prefill_call(64, gp, False))
    jax.block_until_ready(decode_call(8))

    timed("prefill b=64 g=1, DISPATCH only",
          lambda: prefill_call(64, 1, False),
          program="batched_prefill_dispatch", chunk=64, group=1)
    for gp in (1, 2, 4, 8, S):
        np.asarray(prefill_call(64, gp, False))  # warm shape
        timed(f"prefill b=64 g={gp} + firsts fetch",
              lambda gp=gp: prefill_call(64, gp, True),
              program="batched_prefill", chunk=64, group=gp, ctx=512)

    def settled_fetch(gp):
        firsts = prefill_call(64, gp, False)
        time.sleep(0.5)  # compute certainly done; fetch cost only
        t0 = time.perf_counter()
        np.asarray(firsts)
        return (time.perf_counter() - t0) * 1000

    for gp in (1, S):
        vals = [settled_fetch(gp) for _ in range(6)]
        print(f"  settled fetch after g={gp:2d} prefill"
              f"{'':14s} p50 {float(np.median(vals)):8.2f} ms  "
              f"min {min(vals):.2f} max {max(vals):.2f}")
    timed("decode call 8 steps + token fetch",
          lambda: np.asarray(decode_call(8)),
          program="decode", kv_len=512, steps=8)
    timed("decode dispatch only",
          lambda: decode_call(8),
          program="decode_dispatch", kv_len=512, steps=8)
    # Pipelined decode: dispatch N, then fetch the first — models the
    # engine's steady state where fetch overlaps the next call.
    t0 = time.perf_counter()
    toks = [decode_call(8) for _ in range(10)]
    for t in toks:
        np.asarray(t)
    wall = (time.perf_counter() - t0) * 1000
    print(f"  {'10 pipelined decode calls (80 steps)':44s} "
          f"total {wall:8.2f} ms -> {wall / 80:.2f} ms/step")

    # Prefill with a decode call queued in front (the burst situation).
    def queued(gp):
        decode_call(8)
        firsts = prefill_call(64, gp, False)
        np.asarray(firsts)

    timed("decode(8) then prefill g=1 + fetch", lambda: queued(1))
    timed(f"decode(8) then prefill g={S} + fetch", lambda: queued(S))


if __name__ == "__main__":
    try:
        main()
    finally:
        print_programs()
