"""Train the real BPE tokenizer the weight-free bench serves with.

Why this exists: without a checkpoint the engine fell back to the
byte-level tokenizer, which inflates an English prompt ~6x (1 token per
byte vs ~4 bytes/token for a 32k BPE). That pushed the bench's 16-way
burst prefill from the 64-token bucket into the 512-token bucket —
~8k prompt tokens of pure MXU work per burst — and TTFT measured that
inflation, not the serving path (scripts/profile_ttft.py, round 4). The
reference never had this problem because its engines always shipped a
real tokenizer (vLLM HF cache volume, docker-compose.vllm.yml:58-59).

Trains a ByteLevel BPE (llama/GPT-2 style) on the English-heavy text
available offline in the image (repo docs + library docstrings), with
the llama3 + ChatML special tokens used by the in-tree chat templates.
Output: fasttalk_tpu/assets/bench_tokenizer.json (committed; training
is reproducible with this script but needs no network either way).

Usage: python scripts/make_bench_tokenizer.py [--vocab 32000]
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SPECIALS = [
    "<unk>",
    # llama3 family (engine/tokenizer.py render_llama3)
    "<|begin_of_text|>", "<|end_of_text|>", "<|start_header_id|>",
    "<|end_header_id|>", "<|eot_id|>", "<|eom_id|>",
    "<|finetune_right_pad_id|>",
    # ChatML family (render_chatml)
    "<|im_start|>", "<|im_end|>", "<|endoftext|>",
    # Mistral family (render_mistral)
    "<s>", "</s>",
]


def corpus_files(max_mb: int = 24) -> list[str]:
    pats = [
        os.path.join(REPO, "*.md"),
        "/opt/skills/guides/*.md",
        "/opt/venv/lib/python3.12/site-packages/transformers/**/*.py",
        "/opt/venv/lib/python3.12/site-packages/jax/**/*.py",
    ]
    files: list[str] = []
    total = 0
    for pat in pats:
        for f in sorted(glob.glob(pat, recursive=True)):
            sz = os.path.getsize(f)
            if total + sz > max_mb * 2**20:
                return files
            files.append(f)
            total += sz
    return files


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--vocab", type=int, default=32000)
    ap.add_argument("--out", default=os.path.join(
        REPO, "fasttalk_tpu", "assets", "bench_tokenizer.json"))
    args = ap.parse_args()

    from tokenizers import Tokenizer, decoders, pre_tokenizers, processors
    from tokenizers.models import BPE
    from tokenizers.trainers import BpeTrainer

    tok = Tokenizer(BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    tok.post_processor = processors.ByteLevel(trim_offsets=False)

    files = corpus_files()
    print(f"training BPE vocab={args.vocab} on {len(files)} files...",
          file=sys.stderr)
    trainer = BpeTrainer(vocab_size=args.vocab, special_tokens=SPECIALS,
                         show_progress=False)
    tok.train(files, trainer)

    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    tok.save(args.out)
    # Smoke: ratio + specials survive a round trip as single ids.
    sample = ("You are a concise assistant for a realtime voice app. "
              "Explain how a systolic array multiplies matrices.")
    ids = tok.encode(sample, add_special_tokens=False).ids
    print(f"saved {args.out}: vocab={tok.get_vocab_size()}, "
          f"sample {len(sample)} chars -> {len(ids)} tokens "
          f"({len(sample) / len(ids):.1f} chars/token)", file=sys.stderr)
    for s in SPECIALS:
        assert tok.token_to_id(s) is not None, s
        assert len(tok.encode(s, add_special_tokens=False).ids) == 1, s


if __name__ == "__main__":
    main()
