#!/usr/bin/env python
"""Strict Prometheus exposition-format validator.

PR 1 fixed a family of silent /metrics regressions (unescaped HELP
newlines truncating the next line, int-vs-float ``le`` bounds rendering
the same bucket two ways); this script makes that bug class
un-reintroducible by validating the full text a scraper would see:

- line grammar: ``# HELP``/``# TYPE`` comments and
  ``name{labels} value [timestamp]`` samples, nothing else;
- metric and label names against the Prometheus regexes, label values
  properly quoted/escaped, values parseable as Go floats;
- at most one HELP and one TYPE per metric, both BEFORE its samples,
  and every metric's samples contiguous (interleaving is illegal);
- no duplicate series (same name + label set twice);
- histograms: every ``_bucket`` carries ``le``, bounds parse and
  strictly increase, cumulative counts are non-decreasing, the
  ``+Inf`` bucket exists and equals ``_count``, and ``_sum``/
  ``_count`` are present.

Usage:
    python scripts/check_prometheus.py metrics.txt
    curl -s localhost:9092/metrics | python scripts/check_prometheus.py -
    python scripts/check_prometheus.py http://localhost:9092/metrics

Exit 0 when clean; exit 1 listing every problem found. Stdlib-only, and
importable (``validate(text) -> list[str]``) — tests run it against the
live monitoring app's /metrics output (run_tests.sh --slo).
"""

from __future__ import annotations

import re
import sys

_METRIC_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
# One label pair: name="value" with \\, \", \n escapes allowed inside.
_PAIR_RE = re.compile(
    r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"     # metric name
    r"(?:\{(.*)\})?"                   # optional label block
    r" ([^ ]+)"                        # value
    r"(?: ([0-9-]+))?$")               # optional timestamp
_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def _parse_value(raw: str) -> float | None:
    if raw in ("+Inf", "-Inf", "NaN"):
        return {"+Inf": float("inf"), "-Inf": float("-inf"),
                "NaN": float("nan")}[raw]
    try:
        return float(raw)
    except ValueError:
        return None


def _parse_labels(block: str, problems: list[str],
                  where: str) -> dict[str, str] | None:
    """Parse a label block strictly: pairs separated by commas, no
    trailing junk."""
    labels: dict[str, str] = {}
    rest = block
    while rest:
        m = _PAIR_RE.match(rest)
        if m is None:
            problems.append(f"{where}: malformed label block at "
                            f"{rest[:30]!r}")
            return None
        name, value = m.group(1), m.group(2)
        if not _LABEL_RE.match(name):
            problems.append(f"{where}: bad label name {name!r}")
        if name in labels:
            problems.append(f"{where}: duplicate label {name!r}")
        labels[name] = value
        rest = rest[m.end():]
        if rest.startswith(","):
            rest = rest[1:]
        elif rest:
            problems.append(f"{where}: junk after label pair: "
                            f"{rest[:30]!r}")
            return None
    return labels


def _base_name(name: str, typ: str | None) -> str:
    """Samples of a histogram/summary family live under suffixed
    names; map them back to the declared family name."""
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)]
    return name


def validate(text: str) -> list[str]:
    """Validate exposition text; returns a list of problems (empty =
    clean)."""
    problems: list[str] = []
    if text and not text.endswith("\n"):
        problems.append("exposition must end with a newline")
    helps: set[str] = set()
    types: dict[str, str] = {}
    # family -> list of (labels, value) per suffixed sample name
    series_seen: set[tuple[str, tuple[tuple[str, str], ...]]] = set()
    family_order: list[str] = []
    family_done: set[str] = set()
    sampled_families: set[str] = set()
    buckets: dict[str, list[tuple[float, float]]] = {}
    sums: dict[str, float] = {}
    counts: dict[str, float] = {}
    current: str | None = None

    for i, line in enumerate(text.splitlines(), start=1):
        where = f"line {i}"
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(" ", 3)
            if len(parts) >= 2 and parts[1] not in ("HELP", "TYPE"):
                continue  # free comment
            if len(parts) < 3:
                problems.append(f"{where}: malformed comment {line!r}")
                continue
            kind, name = parts[1], parts[2]
            if not _METRIC_RE.match(name):
                problems.append(f"{where}: bad metric name {name!r}")
            if kind == "HELP":
                if name in helps:
                    problems.append(f"{where}: second HELP for {name}")
                helps.add(name)
            else:
                typ = parts[3] if len(parts) > 3 else ""
                if typ not in _TYPES:
                    problems.append(f"{where}: bad TYPE {typ!r} "
                                    f"for {name}")
                if name in types:
                    problems.append(f"{where}: second TYPE for {name}")
                types[name] = typ
            if name in sampled_families:
                problems.append(f"{where}: {kind} for {name} after its "
                                "samples (must precede them)")
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            problems.append(f"{where}: unparseable sample {line!r}")
            continue
        name, label_block, raw_value, _ts = m.groups()
        if not _METRIC_RE.match(name):
            problems.append(f"{where}: bad metric name {name!r}")
        labels = _parse_labels(label_block or "", problems, where)
        if labels is None:
            continue
        value = _parse_value(raw_value)
        if value is None:
            problems.append(f"{where}: unparseable value "
                            f"{raw_value!r}")
            continue
        family = _base_name(name, None)
        if family not in types and name in types:
            family = name
        # Contiguity: once another family's samples started, earlier
        # families must not reappear.
        if current != family:
            if family in family_done:
                problems.append(f"{where}: samples of {family} are "
                                "interleaved with another metric's")
            if current is not None:
                family_done.add(current)
            if family not in family_order:
                family_order.append(family)
            current = family
        sampled_families.add(family)
        key = (name, tuple(sorted(labels.items())))
        if key in series_seen:
            problems.append(f"{where}: duplicate series {name}"
                            f"{dict(labels)}")
        series_seen.add(key)
        if types.get(family) == "histogram":
            if name.endswith("_bucket"):
                le = labels.get("le")
                if le is None:
                    problems.append(f"{where}: histogram bucket "
                                    f"without le label")
                    continue
                bound = _parse_value(le)
                if bound is None:
                    problems.append(f"{where}: unparseable le "
                                    f"{le!r}")
                    continue
                buckets.setdefault(family, []).append((bound, value))
            elif name.endswith("_sum"):
                sums[family] = value
            elif name.endswith("_count"):
                counts[family] = value
            elif name == family:
                problems.append(f"{where}: bare sample {name} for a "
                                "histogram (expected _bucket/_sum/"
                                "_count)")

    for family, typ in types.items():
        if typ != "histogram":
            continue
        bs = buckets.get(family, [])
        if not bs:
            problems.append(f"histogram {family}: no buckets")
            continue
        bounds = [b for b, _ in bs]
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            problems.append(f"histogram {family}: le bounds not "
                            "strictly increasing")
        vals = [v for _, v in bs]
        if any(v2 < v1 for v1, v2 in zip(vals, vals[1:])):
            problems.append(f"histogram {family}: cumulative bucket "
                            "counts decrease")
        if bounds[-1] != float("inf"):
            problems.append(f"histogram {family}: missing +Inf bucket")
        if family not in counts:
            problems.append(f"histogram {family}: missing _count")
        elif bounds[-1] == float("inf") \
                and vals[-1] != counts[family]:
            problems.append(
                f"histogram {family}: +Inf bucket ({vals[-1]}) != "
                f"_count ({counts[family]})")
        if family not in sums:
            problems.append(f"histogram {family}: missing _sum")
    return problems


def _read(source: str) -> str:
    if source == "-":
        return sys.stdin.read()
    if source.startswith(("http://", "https://")):
        from urllib.request import urlopen

        with urlopen(source, timeout=10) as resp:
            return resp.read().decode("utf-8")
    with open(source, encoding="utf-8") as fp:
        return fp.read()


def main(argv: list[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if len(argv) != 1:
        print("usage: check_prometheus.py <file | - | http://...>",
              file=sys.stderr)
        return 2
    try:
        text = _read(argv[0])
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    problems = validate(text)
    if problems:
        for p in problems:
            print(p, file=sys.stderr)
        print(f"{len(problems)} problem(s)", file=sys.stderr)
        return 1
    print("exposition format OK "
          f"({len(text.splitlines())} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
