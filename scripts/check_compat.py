#!/usr/bin/env python3
"""Compat-matrix lint (docs/KVCACHE.md, SPEC_DECODE.md, STRUCTURED.md,
QUANTIZATION.md; run_tests.sh --roofline).

The docs carry compat tables ("rejected — <reason>" / "supported")
and Config/engine carry the actual guards. Each has drifted from the
other before: a guard lifted without its doc row (stale "rejected"
scares users off a working path) or a doc row flipped to "supported"
without the guard actually lifting. This lint cross-checks both
surfaces on every run:

1. DYNAMIC — build a real `Config` per documented combination and
   assert it is accepted or rejected exactly as the doc row claims,
   with the doc's named reason a substring of the actual ValueError.
2. STATIC — for rejections enforced at the engine seam too, assert
   the reason phrase appears in engine.py source, so the two error
   messages can't drift apart.
3. DOC — assert each doc file still contains the row text this table
   encodes, so editing a doc row without editing this table (or vice
   versa) fails CI instead of shipping a contradiction.

Exit 0 = clean; exit 1 = problems, each printed on its own line.
"""

from __future__ import annotations

import sys
from dataclasses import dataclass, field
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

ENGINE = REPO / "fasttalk_tpu" / "engine" / "engine.py"

# The phrase both seams must agree on for the one remaining
# kernel-adjacent rejection (Config._validate AND TPUEngine.__init__).
SPEC_SCALE_REASON = ("the spec carry does not thread the "
                     "scale arrays through the verify block")


@dataclass
class Case:
    name: str
    kwargs: dict
    # None -> Config must construct; str -> Config must raise
    # ValueError containing this substring (the documented reason).
    reject_reason: str | None = None
    # (doc path relative to repo, substring the doc must contain) —
    # the doc row this combination's behaviour is documented by.
    docs: list[tuple[str, str]] = field(default_factory=list)
    # Reason must also appear verbatim in engine.py (seam mirror).
    engine_mirror: bool = False


CASES = [
    # --- KV_QUANT=int8 tier (docs/KVCACHE.md quantized compat table)
    Case("kv_int8 x pallas attention composes",
         dict(kv_quant="int8", spec_decode="off",
              use_pallas_attention=True),
         docs=[("docs/KVCACHE.md",
                "dequantizes after the DMA, so int8 bytes are what "
                "cross HBM"),
               ("docs/ROOFLINE.md",
                "dequant happens in VMEM *after* the DMA")]),
    Case("kv_int8 x spec decode rejected (scale carry)",
         dict(kv_quant="int8", spec_decode="ngram"),
         reject_reason=SPEC_SCALE_REASON,
         docs=[("docs/KVCACHE.md", SPEC_SCALE_REASON),
               ("docs/SPEC_DECODE.md", SPEC_SCALE_REASON)],
         engine_mirror=True),
    Case("kv_int8 x mesh rejected",
         dict(kv_quant="int8", spec_decode="off", tp_size=2),
         reject_reason="single-device only",
         docs=[("docs/KVCACHE.md",
                "rejected — the scale arrays do not shard")]),
    Case("kv_int8 x SPMD rejected",
         dict(kv_quant="int8", spec_decode="off", spmd_role="leader",
              spmd_addr="h:1", spmd_followers=1),
         reject_reason="multi-host SPMD"),

    # --- KV_LAYOUT=paged tier (docs/KVCACHE.md paged compat table)
    Case("paged x pallas attention composes",
         dict(kv_layout="paged", use_pallas_attention=True),
         docs=[("docs/KVCACHE.md",
                "Pallas decode attention | supported")]),
    Case("paged x spec decode composes",
         dict(kv_layout="paged", spec_decode="ngram"),
         docs=[("docs/KVCACHE.md",
                "speculative decoding | supported")]),
    Case("paged x mesh rejected",
         dict(kv_layout="paged", tp_size=2),
         reject_reason="single-device only",
         docs=[("docs/KVCACHE.md",
                "rejected — the pool and tables are host-orchestrated")]),
    Case("paged x kv_int8 x pallas composes (fused paged kernel)",
         dict(kv_layout="paged", kv_quant="int8", spec_decode="off",
              use_pallas_attention=True)),

    # --- spec decode (docs/SPEC_DECODE.md)
    Case("spec x pallas attention composes (multi-token-q verify)",
         dict(spec_decode="ngram", use_pallas_attention=True),
         docs=[("docs/SPEC_DECODE.md",
                "multi-token-q generalisation")]),

    # --- structured decoding (docs/STRUCTURED.md compat matrix)
    Case("structured=on x pallas attention composes",
         dict(structured_mode="on", use_pallas_attention=True),
         docs=[("docs/STRUCTURED.md",
                "rides the scatter path since the multi-token-q "
                "generalisation")]),
    Case("structured=on x mesh rejected",
         dict(structured_mode="on", tp_size=2),
         reject_reason="single-device only"),

    # --- int4 weight tier (docs/QUANTIZATION.md compat matrix)
    Case("weight int4 x pallas attention composes",
         dict(weight_quant="int4", use_pallas_attention=True),
         docs=[("docs/QUANTIZATION.md",
                "the decode-attention kernel is orthogonal to the "
                "weight tier")]),
    Case("weight int4 x mesh rejected",
         dict(weight_quant="int4", tp_size=2),
         reject_reason="sharded load/init path is unvalidated",
         docs=[("docs/QUANTIZATION.md",
                "sharded load/init unvalidated")]),
]


def _norm(s: str) -> str:
    """Collapse whitespace so phrases wrapped across source/doc lines
    still match their single-line form."""
    return " ".join(s.split())


def main() -> int:
    from fasttalk_tpu.utils.config import Config

    problems: list[str] = []
    # Strip quotes so phrases split across adjacent string literals
    # ("... the " "scale arrays ...") still match their joined form.
    engine_src = _norm(ENGINE.read_text().replace('"', ' '))

    for case in CASES:
        try:
            Config(**case.kwargs)
            err = None
        except ValueError as e:
            err = _norm(str(e))

        if case.reject_reason is None:
            if err is not None:
                problems.append(
                    f"{case.name}: doc says supported but Config "
                    f"rejects: {err}")
        else:
            if err is None:
                problems.append(
                    f"{case.name}: doc says rejected "
                    f"({case.reject_reason!r}) but Config accepts — "
                    "lifted guard without updating the doc table and "
                    "this lint?")
            elif _norm(case.reject_reason) not in err:
                problems.append(
                    f"{case.name}: Config rejects but without the "
                    f"documented reason {case.reject_reason!r}; "
                    f"actual: {err}")

        for doc_rel, needle in case.docs:
            doc = REPO / doc_rel
            if not doc.exists():
                problems.append(f"{case.name}: {doc_rel} missing")
            elif _norm(needle) not in _norm(doc.read_text()):
                problems.append(
                    f"{case.name}: {doc_rel} no longer contains the "
                    f"row text {needle!r} — doc and guard drifted")

        if case.engine_mirror \
                and _norm(case.reject_reason) not in engine_src:
            problems.append(
                f"{case.name}: reason {case.reject_reason!r} not "
                "found in engine.py — the engine seam no longer "
                "mirrors the Config rejection")

    if problems:
        for p in problems:
            print(f"PROBLEM: {p}")
        return 1
    print(f"check_compat: {len(CASES)} documented combinations match "
          "live Config behaviour (docs/KVCACHE.md, SPEC_DECODE.md, "
          "STRUCTURED.md, QUANTIZATION.md)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
