"""In-program decode cost attribution on the real chip.

One jitted K-step decode program per variant; per-piece cost =
difference of MARGINAL per-step time (steps 16 vs 48) between a variant
and the base. Marginal timing cancels the relay round trip and all
per-call fixed cost; swapping one piece per variant attributes the
remainder. (One-op micro-benches are useless on this attach path: each
eager dispatch carries multi-ms relay overhead that the real engine
never pays, profile_decode.py history.)

Usage: python scripts/profile_variants.py [variant ...]
Variants: bf16 base mmxla headxla attnpallas greedy nohead
"""

from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fasttalk_tpu.models.configs import get_model_config
from fasttalk_tpu.models.llama import KVCache, forward, init_cache
from fasttalk_tpu.models.loader import init_params_device
from fasttalk_tpu.ops.quant import (embed_lookup, matmul_tied,
                                    quantize_params)
from fasttalk_tpu.ops.quant import matmul as qmm
from fasttalk_tpu.ops import rope as rope_mod
from fasttalk_tpu.ops.attention import attend
from fasttalk_tpu.ops.sampling import sample_tokens
from fasttalk_tpu.models.llama import rms_norm, _write_kv
from fasttalk_tpu.utils.compile_cache import enable_compilation_cache

SLOTS = 16
KV_LEN = 512
REPS = 8


def step_fn(params, cfg, cur, pos, active, temps, topks, topps, key,
            sk, sv, *, mm_pallas, head_pallas, attn_pallas, sampling,
            use_head):
    """One decode step, pieces selectable."""
    b = SLOTS
    inv_freq = jnp.asarray(rope_mod.rope_frequencies(
        cfg.head_dim, cfg.rope_theta, cfg.rope_scaling))
    tokens = cur[:, None]
    positions = pos[:, None]
    x = embed_lookup(params["embed"], tokens, params["final_norm"].dtype)
    act = jnp.logical_and(active, pos < KV_LEN)

    def layer(x, scanned):
        lp, ck, cv = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = (qmm(h, lp["wq"], mm_pallas), qmm(h, lp["wk"], mm_pallas),
                   qmm(h, lp["wv"], mm_pallas))
        q = q.reshape(b, 1, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, 1, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(b, 1, cfg.num_kv_heads, cfg.head_dim)
        q = rope_mod.apply_rope(q, positions, inv_freq)
        k = rope_mod.apply_rope(k, positions, inv_freq)
        ck = _write_kv(ck, k, pos, act)
        cv = _write_kv(cv, v, pos, act)
        if attn_pallas:
            from fasttalk_tpu.ops.pallas_attention import decode_attend

            o = decode_attend(q[:, 0], ck, cv, positions[:, 0] + 1)[:, None]
        else:
            o = attend(q, ck, cv, positions)
        x = x + qmm(o.reshape(b, 1, cfg.q_dim), lp["wo"], mm_pallas)
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        gate = jax.nn.silu(qmm(h, lp["w_gate"], mm_pallas).astype(jnp.float32))
        up = qmm(h, lp["w_up"], mm_pallas).astype(jnp.float32)
        x = x + qmm((gate * up).astype(x.dtype), lp["w_down"], mm_pallas)
        return x, (ck, cv)

    x, (sk, sv) = jax.lax.scan(layer, x, (params["layers"], sk, sv))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    if use_head:
        logits = matmul_tied(x, params["embed"], head_pallas)
        lg = logits[:, -1]
        if sampling == "greedy":
            nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
        else:
            key, sub = jax.random.split(key)
            nxt = sample_tokens(lg, sub, temps, topks, topps,
                                method=sampling)
    else:
        nxt = (cur + x[:, 0, 0].astype(jnp.int32) % 7) % 1000
    return nxt, key, sk, sv


def make_call(cfg, steps, **kw):
    @partial(jax.jit, donate_argnums=(1,), static_argnames=())
    def call(params, cache, cur, pos, active, temps, topks, topps, rng):
        sk = jax.lax.slice_in_dim(cache.k, 0, KV_LEN, axis=2)
        sv = jax.lax.slice_in_dim(cache.v, 0, KV_LEN, axis=2)

        def body(carry, _):
            sk, sv, cur, pos, key = carry
            nxt, key, sk, sv = step_fn(params, cfg, cur, pos, active,
                                       temps, topks, topps, key, sk, sv,
                                       **kw)
            act = jnp.logical_and(active, pos < KV_LEN)
            pos = pos + act.astype(pos.dtype)
            return (sk, sv, nxt, pos, key), nxt

        (sk, sv, cur, pos, rng), toks = jax.lax.scan(
            body, (sk, sv, cur, pos, rng), None, length=steps)
        nk = jax.lax.dynamic_update_slice_in_dim(cache.k, sk, 0, axis=2)
        nv = jax.lax.dynamic_update_slice_in_dim(cache.v, sv, 0, axis=2)
        return KVCache(nk, nv), toks

    return call


VARIANTS = {
    "bf16": dict(mm_pallas=False, head_pallas=False, attn_pallas=False,
                 sampling="fast", use_head=True, quant=False),
    "base": dict(mm_pallas=True, head_pallas=True, attn_pallas=False,
                 sampling="fast", use_head=True, quant=True),
    "mmxla": dict(mm_pallas=False, head_pallas=True, attn_pallas=False,
                  sampling="fast", use_head=True, quant=True),
    "headxla": dict(mm_pallas=True, head_pallas=False, attn_pallas=False,
                    sampling="fast", use_head=True, quant=True),
    "attnpallas": dict(mm_pallas=True, head_pallas=True, attn_pallas=True,
                       sampling="fast", use_head=True, quant=True),
    "greedy": dict(mm_pallas=True, head_pallas=True, attn_pallas=False,
                   sampling="greedy", use_head=True, quant=True),
    "nohead": dict(mm_pallas=True, head_pallas=True, attn_pallas=False,
                   sampling="greedy", use_head=False, quant=True),
}


def main():
    names = sys.argv[1:] or list(VARIANTS)
    enable_compilation_cache("", None)
    cfg = get_model_config("llama3.2:1b")
    print(f"devices: {jax.devices()}", flush=True)
    params_bf16 = init_params_device(cfg, jnp.bfloat16)
    jax.block_until_ready(params_bf16)
    qparams = None

    for name in names:
        kw = dict(VARIANTS[name])
        quant = kw.pop("quant")
        if quant and qparams is None:
            qparams = quantize_params(
                jax.tree.map(lambda x: x, params_bf16))
            jax.block_until_ready(jax.tree.leaves(qparams))
        params = qparams if quant else params_bf16
        res = {}
        for steps in (16, 48):
            cache = init_cache(cfg, SLOTS, 2048, jnp.bfloat16)
            cur = jnp.zeros((SLOTS,), jnp.int32)
            pos = jnp.full((SLOTS,), 100, jnp.int32)
            active = jnp.ones((SLOTS,), bool)
            temps = jnp.full((SLOTS,), 0.7, jnp.float32)
            topks = jnp.full((SLOTS,), 40, jnp.int32)
            topps = jnp.full((SLOTS,), 0.9, jnp.float32)
            rng = jax.random.PRNGKey(0)
            fn = make_call(cfg, steps, **kw)
            cache, toks = fn(params, cache, cur, pos, active, temps,
                             topks, topps, rng)
            np.asarray(toks)
            cur = jnp.asarray(np.asarray(toks[-1]) % cfg.vocab_size)
            t0 = time.perf_counter()
            for _ in range(REPS):
                cache, toks = fn(params, cache, cur, pos, active, temps,
                                 topks, topps, rng)
                cur = toks[-1] % cfg.vocab_size
            np.asarray(toks)
            res[steps] = (time.perf_counter() - t0) / REPS
            del cache
        marg = (res[48] - res[16]) / 32
        print(f"{name:12s}: marginal {marg * 1e3:6.2f} ms/step "
              f"(16: {res[16] * 1e3:6.1f}  48: {res[48] * 1e3:6.1f} ms/call)",
              flush=True)


if __name__ == "__main__":
    main()
