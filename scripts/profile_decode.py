"""Kernel-level decode profile: bf16 vs int8(XLA) vs int8(Pallas).

Answers VERDICT r2 weak #1/#6 with measurements instead of estimates:
  - per-call and per-step cost of the K-step decode call at several
    steps_per_call values (separates fixed per-call cost from marginal
    per-step cost);
  - whether the Pallas int8 matmul actually beats the XLA int8 lowering
    and bf16 on the stacked layer weights (isolated streaming bench);
  - the cost split: layer scan vs lm_head matmul vs sampling.

Run on the bench host: python scripts/profile_decode.py [model]
"""

from __future__ import annotations

import sys
import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from fasttalk_tpu.models.configs import get_model_config
from fasttalk_tpu.models.llama import KVCache, forward, init_cache
from fasttalk_tpu.models.loader import init_params_device
from fasttalk_tpu.observability.perf import PerfLedger, program_key
from fasttalk_tpu.observability.trace import Tracer
from fasttalk_tpu.ops.quant import quantize_params
from fasttalk_tpu.ops.sampling import sample_tokens
from fasttalk_tpu.utils.compile_cache import enable_compilation_cache

SLOTS = 16
KV_LEN = 512
REPS = 10
RT = 0.0  # measured relay round-trip latency, set in main()

# Standalone step ledger: every timed loop below is also fed in as
# device intervals stamped with its program key, so the script ends
# with the same per-program attribution table GET /perf serves live —
# one vocabulary for offline profiles and production telemetry.
_TRACER = Tracer(enabled=True)
_LEDGER = PerfLedger(tracer=_TRACER, window_s=3600.0)


def record_loop(kind: str, reps: int, dt: float, tokens: int = 0,
                **attrs) -> None:
    """Feed a measured loop (reps back-to-back calls of dt seconds,
    ending now) into the ledger as token-stat-free engine_op rows."""
    prog = program_key(kind, **attrs)
    end = time.monotonic()
    for i in range(reps):
        t1 = end - (reps - 1 - i) * dt
        _TRACER.step("engine_op", t1 - dt, t1, kind=kind, program=prog,
                     **({"tokens": tokens} if tokens else {}))


def print_programs() -> None:
    progs = (_LEDGER.report().get("programs") or {})
    rows = progs.get("by_program") or []
    if not rows:
        return
    print("== per-program device time (observability/perf.py "
          "ledger) ==", flush=True)
    for e in rows:
        print(f"  {e['busy_s']:8.3f}s {e['frac_of_busy']:7.1%} "
              f"x{e['calls']:<4d} {e['program']}")
    print(f"  {progs['total_busy_s']:8.3f}s total device busy "
          f"(per-program seconds sum to this by construction)")


def measure_rt():
    """One-way dispatch + tiny-fetch round trip of the attach path."""
    global RT
    one = jnp.ones((8,), jnp.int32)
    f = jax.jit(lambda a: a + 1)
    a = f(one)
    np.asarray(a)
    ts = []
    for _ in range(10):
        t0 = time.perf_counter()
        a = f(a)
        np.asarray(a)
        ts.append(time.perf_counter() - t0)
    RT = float(np.median(ts))
    print(f"relay round trip (tiny jit + fetch): {RT * 1e3:.1f} ms",
          flush=True)


def timeit(fn, *args, reps=REPS, donate_idx=None):
    """Median wall time of fn(*args); handles donated args by
    regenerating them per rep (cheap: donated cache buffer reuse)."""
    out = fn(*args)
    jax.block_until_ready(out)
    ts = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        ts.append(time.perf_counter() - t0)
    return float(np.median(ts)), out


def bench_weight_stream(cfg, params, label):
    """Stream every layer's w_gate/w_up/w_down through a matmul under a
    scan — the shape of the decode hot loop, minus attention/sampling."""
    from fasttalk_tpu.ops.quant import matmul as qmm

    x = jnp.ones((SLOTS, 1, cfg.hidden_size), jnp.bfloat16)

    def body(x, lp):
        h = qmm(x, lp["w_gate"], True)
        u = qmm(x, lp["w_up"], True)
        y = qmm((h * u).astype(x.dtype), lp["w_down"], True)
        return (x + y).astype(x.dtype), ()

    @jax.jit
    def run(x, layers):
        y, _ = jax.lax.scan(body, x, layers)
        return y

    layers = {k: params["layers"][k] for k in ("w_gate", "w_up", "w_down")}

    def nbytes(t):
        return sum(v.nbytes for v in jax.tree.leaves(t))

    # Chain the output into the next call's input: identical (program,
    # args) pairs can be served from a cache on relayed backends, which
    # would report impossible bandwidth numbers.
    # np.asarray (real host fetch) is the only reliable sync on the
    # relayed backend — block_until_ready returns early there.
    # Chained dispatch, ONE trailing fetch: per-call time is total/REPS
    # minus the single ~100ms relay round trip — exactly how the
    # pipelined engine experiences the device.
    x = run(x, layers)
    np.asarray(x)
    t0 = time.perf_counter()
    for _ in range(REPS):
        x = run(x, layers)
    np.asarray(x)
    dt = (time.perf_counter() - t0 - RT) / REPS
    gb = nbytes(layers) / 1e9
    print(f"  mlp-stream {label:12s}: {dt * 1e3:7.2f} ms "
          f"({gb:.2f} GB -> {gb / dt:.0f} GB/s)")
    record_loop("mlp_stream", REPS, dt, weights=label)
    return dt


def make_decode_call(cfg, steps, pallas_int8, sampling="fast"):
    # params is an ARGUMENT (as in the engine) — closing over it would
    # capture 2.5GB of constants into the lowered program.
    @partial(jax.jit, donate_argnums=(1,))
    def decode_call(params, cache, cur, pos, active, temps, topks, topps,
                    rng):
        ck = jax.lax.slice_in_dim(cache.k, 0, KV_LEN, axis=2)
        cv = jax.lax.slice_in_dim(cache.v, 0, KV_LEN, axis=2)

        def step(carry, _):
            sk, sv, cur, pos, key = carry
            key, sub = jax.random.split(key)
            act = jnp.logical_and(active, pos < KV_LEN)
            logits, small = forward(params, cfg, cur[:, None], pos[:, None],
                                    KVCache(sk, sv), pos, write_mask=act,
                                    pallas_int8=pallas_int8)
            nxt = sample_tokens(logits[:, -1], sub, temps, topks, topps,
                                method=sampling)
            pos = pos + act.astype(pos.dtype)
            return (sk, sv, nxt, pos, key), nxt

        (ck, cv, cur, pos, rng), toks = jax.lax.scan(
            step, (ck, cv, cur, pos, rng), None, length=steps)
        nk = jax.lax.dynamic_update_slice_in_dim(cache.k, ck, 0, axis=2)
        nv = jax.lax.dynamic_update_slice_in_dim(cache.v, cv, 0, axis=2)
        return KVCache(nk, nv), toks

    return decode_call


def profile_variant(cfg, params, label, pallas_int8):
    cache = init_cache(cfg, SLOTS, 2048, jnp.bfloat16)
    cur = jnp.zeros((SLOTS,), jnp.int32)
    pos = jnp.full((SLOTS,), 100, jnp.int32)
    active = jnp.ones((SLOTS,), bool)
    temps = jnp.full((SLOTS,), 0.7, jnp.float32)
    topks = jnp.full((SLOTS,), 40, jnp.int32)
    topps = jnp.full((SLOTS,), 0.9, jnp.float32)
    rng = jax.random.PRNGKey(0)

    results = {}
    for steps in (8, 32):
        fn = make_decode_call(cfg, steps, pallas_int8)
        # warm compile; chain cur/rng through calls so no two calls have
        # identical inputs (relay-cache defeat), exactly as the engine
        # chains its decode state.
        cache, toks = fn(params, cache, cur, pos, active, temps, topks,
                         topps, rng)
        np.asarray(toks)
        cur = toks[-1]
        t0 = time.perf_counter()
        for _ in range(REPS):
            cache, toks = fn(params, cache, cur, pos, active, temps,
                             topks, topps, rng)
            cur = toks[-1]
        np.asarray(toks)
        dt = (time.perf_counter() - t0 - RT) / REPS
        results[steps] = dt
        print(f"  {label:14s} steps={steps:3d}: {dt * 1e3:7.2f} ms/call "
              f"= {dt / steps * 1e3:6.2f} ms/step "
              f"({SLOTS * steps / dt:6.0f} agg tok/s)")
        record_loop("profile_decode", REPS, dt,
                    tokens=SLOTS * steps, weights=label, steps=steps)
    # fixed-cost estimate from the 8->32 line
    per_step = (results[32] - results[8]) / 24
    fixed = results[8] - 8 * per_step
    print(f"  {label:14s} marginal {per_step * 1e3:.2f} ms/step, "
          f"fixed {fixed * 1e3:.2f} ms/call")
    del cache
    return results


def main():
    name = sys.argv[1] if len(sys.argv) > 1 else "llama3.2:1b"
    section = sys.argv[2] if len(sys.argv) > 2 else "all"
    enable_compilation_cache("", None)
    cfg = get_model_config(name)
    measure_rt()
    print(f"devices: {jax.devices()}  model: {cfg.name} "
          f"({cfg.param_count() / 1e9:.2f}B)")

    if section in ("all", "bf16"):
        print("== bf16 ==", flush=True)
        params = init_params_device(cfg, jnp.bfloat16)
        jax.block_until_ready(params)
        bench_weight_stream(cfg, params, "bf16")
        profile_variant(cfg, params, "bf16", pallas_int8=False)
        return

    params = init_params_device(cfg, jnp.bfloat16)
    qparams = quantize_params(params)
    jax.block_until_ready(qparams)
    del params
    if section in ("all", "int8xla"):
        print("== int8 (XLA dequant) ==", flush=True)
        bench_weight_stream(cfg, qparams, "int8-xla")
        profile_variant(cfg, qparams, "int8-xla", pallas_int8=False)
        if section != "all": return
    if section in ("all", "int8pallas"):
        print("== int8 (Pallas kernel) ==", flush=True)
        profile_variant(cfg, qparams, "int8-pallas", pallas_int8=True)
        if section != "all": return

    if section not in ("all", "pieces"):
        return
    # Cost split: lm_head + sampling
    print("== pieces ==", flush=True)
    x = jnp.ones((SLOTS, cfg.hidden_size), jnp.bfloat16)
    emb = qparams.get("lm_head", qparams["embed"])

    @jax.jit
    def lm_head(x, emb):
        if isinstance(emb, dict):
            q = emb.get("qt", emb.get("q"))  # untied head stores [V, D]
            return (x @ q.astype(x.dtype).T
                    if q.shape[0] == cfg.vocab_size
                    else x @ q.astype(x.dtype)) * 1.0
        w = emb.T if emb.shape[0] == cfg.vocab_size else emb
        return (x @ w).astype(jnp.float32)

    logits = lm_head(x, emb)
    np.asarray(logits[:, :8])
    ts = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        logits = lm_head(x, emb)
        np.asarray(logits[:, :8])
        x = (x + logits[:, :cfg.hidden_size].astype(x.dtype) * 1e-6)
        ts.append(time.perf_counter() - t0)
    dt = float(np.median(ts))
    w0 = jax.tree.leaves(emb)[0]
    print(f"  lm_head matmul: {dt * 1e3:.2f} ms "
          f"({np.prod(w0.shape) * w0.dtype.itemsize / 1e9 / dt:.0f} GB/s)")
    lg = jnp.asarray(np.random.randn(SLOTS, cfg.vocab_size), jnp.bfloat16)
    for m in ("fast", "exact"):
        fn = jax.jit(partial(sample_tokens, method=m))
        args = (jax.random.PRNGKey(0), jnp.full((SLOTS,), .7),
                jnp.full((SLOTS,), 40, jnp.int32), jnp.full((SLOTS,), .9))
        t = fn(lg, *args)
        np.asarray(t)
        ts = []
        for _ in range(REPS):
            t0 = time.perf_counter()
            t = fn(lg, *args)
            np.asarray(t)
            lg = lg.at[0, 0].add(1e-3)
            ts.append(time.perf_counter() - t0)
        dt = float(np.median(ts))
        print(f"  sampling {m:5s}: {dt * 1e3:.2f} ms")


if __name__ == "__main__":
    try:
        main()
    finally:
        print_programs()
