"""Benchmark: streamed output tokens/sec END TO END over WebSocket.

Measures the BASELINE north-star metric — WebSocket output tok/s and
p50 TTFT for Llama-3.2-1B, 1 and N concurrent sessions — by starting
the REAL server (WebSocketLLMServer on aiohttp, the same app
`main.py websocket` serves) and driving N `ws://` clients through the
full JSON protocol on loopback. Every counted token crossed a real
WebSocket (VERDICT r2 asked exactly this; the r2 bench stopped at the
engine's async seam).

``BENCH_MODE=engine`` falls back to the engine-seam measurement
(no sockets) for isolating engine regressions.

``BENCH_MODE=fleet`` runs the router scale-out scenario
(docs/ROUTER.md): N in-process CPU replicas behind a FleetRouter behind
the real WS server vs a single replica with the same per-replica slot
count — aggregate tok/s measures what scaling out buys — then kills the
most-loaded replica mid-stream and reports failover-resume latency
(every affected stream must see a ``resumed`` frame, never an error).
It then runs the session-fabric pair: (1) drain-migrate vs
drain-release follow-up TTFT on long parked sessions (cross-replica KV
migration must beat re-prefill), and (2) a rolling restart of N
replicas under live streams (drain → kill → restart each in turn) with
zero client-visible error frames — only ``resumed`` events. Finally the
disaggregation pair (docs/ROUTER.md "Disaggregated prefill/decode"): a
mid-decode long-prompt burst against a role-split fleet (prefill tier
hands finished KV to the decode tier over the migration wire) vs a
mixed control — role-split must protect decode inter-token p99 with
TTFT inside the priced-migration budget and zero error frames.

``BENCH_MODE=disagg`` runs only that disaggregation pair and prints
the decode ITL p99 gain (role-split over mixed) as its headline.

``BENCH_MODE=longctx`` runs the quantized-KV capacity scenario
(docs/KVCACHE.md "Quantized tier"): long-context sessions parked into
a FIXED ``KV_HOST_BUDGET_MB``, int8 KV (``KV_QUANT=int8``) vs the bf16
control in subprocess-isolated phases — reports parked-session
capacity per budget (headline: the ratio, expected ~2x), restore-
latency p50 both ways, and decode tok/s (must stay within noise).

``BENCH_MODE=paged`` runs the paged-KV capacity scenario
(docs/KVCACHE.md "Paged tier"): a mixed-context fleet on a FIXED
KV-row budget, dense layout (admission priced at slots x max_len) vs
paged block tables (priced at blocks in use) in subprocess-isolated
phases — reports peak concurrent sessions per layout (headline: the
ratio), the same-slot-count short-context decode tok/s pair (the
gather/scatter overhead bound, target within 10%), and aliased-prefix
HBM savings.

``BENCH_MODE=radix`` runs the automatic-prefix-cache scenario
(docs/KVCACHE.md "Automatic prefix cache"): a multi-turn agent
workload that re-submits its growing transcript every turn under a
FRESH session id (the stateless-proxy pattern — same-session resident
reuse can never serve it), radix on (``KV_RADIX_ENABLED=true``) vs
off in subprocess-isolated phases — reports follow-up-turn TTFT both
ways (headline: the speedup, acceptance >= 2x), the tree's hit rate
and bytes saved.

``BENCH_MODE=roofline`` runs the measured-vs-ceiling attribution sweep
(docs/ROOFLINE.md): every decode configuration the compat matrix
serves — (kv_quant x kv_layout x kernel) cells from
``BENCH_RF_CONFIGS`` crossed with the ``BENCH_RF_STEPS``
steps-per-call/fetch-cadence axis — each in its own subprocess at full
slot occupancy, reporting tok/s NEXT TO the perf ledger's
decomposition (device-busy/host-gap fractions, MFU, KV + weight read
bandwidth, and the first-order HBM ceiling fraction).

``BENCH_MODE=int4`` runs the weight-tier capacity scenario
(docs/QUANTIZATION.md): a FIXED device-HBM budget (default 1.5x the
bf16 weight footprint, ``BENCH_I4_BUDGET_MB`` to override) priced per
tier with the SAME math the factory's admission check uses
(engine/factory.py weight_bytes_by_tier) — the headline is the
resident sessions x context envelope ratio (int4+scales vs bf16,
expected >= 2x: whatever the weights stop eating, the KV cache gets) —
plus measured decode tok/s per tier (off/int8/int4) in
subprocess-isolated phases (int4 must stay within noise of int8: both
stream the same dequant-fused matmul shape).

``BENCH_MODE=structured`` runs the constrained-decoding scenario
(docs/STRUCTURED.md): per-step mask-apply overhead vs an unconstrained
control (target <5% tok/s), and jump-forward's forced-token fraction +
e2e delta on a forced-chain-heavy schema, greedy, engine-seam.

``BENCH_MODE=chaos`` runs the recovery-path scenario
(docs/RESILIENCE.md): (1) a failpoints-off control — off vs
armed-but-inert (p=0 rule on the decode dispatch seam) must agree
within 1% tok/s, proving FAULT_POINTS-unset costs nothing; (2)
engine-restart MTTR p50 over injected crash_thread drills
(crash-detected -> supervised restart -> first post-restart token);
(3) router failover resume-latency p50 (kill a replica mid-decode
under a FakeEngine fleet — the routing layer's recovery deadline).

``BENCH_MODE=profiler`` runs the continuous-profiler overhead control
(docs/OBSERVABILITY.md "Continuous profiler and program attribution"):
decode tok/s with the host stack sampler off vs on at ``PROF_HZ``,
pairwise-interleaved like the chaos failpoints control — the headline
is the median on/off delta (target |delta| < 1%), reported next to the
host-gap cause decomposition and per-program attribution the ON
phases produced.

``BENCH_MODE=overload`` runs the admission-control scenario
(docs/SCHEDULING.md): an OPEN-LOOP arrival process (one request every
``BENCH_ARRIVAL_MS`` ms for ``BENCH_OVERLOAD_S`` s, regardless of
completions — the regime where the r1 unbounded queue grew without
bound) against a bounded scheduler, reporting shed rate, expiry rate,
max observed queue depth vs the bound, and admitted-request queue-wait
p50/p95/p99. The headline value is GOODPUT: streamed tokens/s of
admitted requests while the excess is being shed with retry_after.

Weights are random-init (no checkpoint in the image): compute cost is
identical to real weights, which is what throughput measures.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N}
vs_baseline compares against the reference's published ~150 tok/s for
llama3.2:1b on an RTX 3090 (reference: README.md:474, BASELINE.md).
"""

from __future__ import annotations

import asyncio
import json
import os
import statistics
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def slo_goodput_summary() -> tuple[float | None, str]:
    """(lifetime goodput of the interactive class or None, alert
    state) from the process SLO engine — the bench's 'did the admitted
    requests actually meet the promise' number."""
    from fasttalk_tpu.observability.slo import get_slo

    cls = get_slo().snapshot()["classes"].get("interactive", {})
    return ((cls.get("totals") or {}).get("goodput"),
            cls.get("alert", "ok"))


def fmt_goodput(goodput: float | None) -> str:
    return "n/a" if goodput is None else f"{goodput:.1%}"


def reset_slo_after_warmup() -> None:
    """Warmup requests ate XLA compiles; their blown latencies are not
    the steady state the goodput headline claims."""
    from fasttalk_tpu.observability.slo import reset_slo

    reset_slo()


def perf_attribution() -> dict | None:
    """Step-ledger digest (observability/perf.py) over the measured
    window: occupancy, padding-waste fraction, wall-time decomposition
    and MFU next to the tok/s headline, so BENCH_*.json says not just
    how fast but WHERE the remaining time went. None when the engine
    recorded no step telemetry (tracing disabled / remote provider)."""
    from fasttalk_tpu.observability.perf import get_perf

    s = get_perf().summary()
    return s if s.get("device_busy_frac") is not None else None


def _child_env(**overrides: str) -> dict:
    """Environment for a bench subprocess phase. Children log at
    WARNING unless the caller pinned LOG_LEVEL themselves: child
    stderr lands in the captured bench tail, and per-connection INFO
    lines from a warmed engine were drowning the summary lines the
    tail exists for (BENCH_r05.json)."""
    env = dict(os.environ)
    env.setdefault("LOG_LEVEL", "WARNING")
    env.update(overrides)
    return env


BASELINE_TOKS = 150.0  # reference llama3.2:1b on RTX 3090 (README.md:474)
# Env overrides are for smoke-testing on CPU; the driver runs defaults.
MODEL = os.environ.get("BENCH_MODEL", "llama3.2:1b")
NUM_SESSIONS = int(os.environ.get("BENCH_SESSIONS", "16"))
MAX_TOKENS = int(os.environ.get("BENCH_MAX_TOKENS", "128"))
MODE = os.environ.get("BENCH_MODE", "ws")
PORT = int(os.environ.get("BENCH_PORT", "18613"))  # relay squats 81xx
# Fixed-length generations for TRAINED checkpoints (e.g.
# BENCH_MODEL=tinychat MODEL_PATH=fasttalk_tpu/assets
# BENCH_IGNORE_EOS=1): a trained model answers the bench prompt with a
# short reply + EOS, which measures nothing; ignore_eos decodes the
# full budget. Irrelevant for random-init weights (EOS ~never sampled).
IGNORE_EOS = os.environ.get("BENCH_IGNORE_EOS", "") == "1"
PROMPT = ("You are a concise assistant for a realtime voice app. "
          "Explain, in plain language, how a systolic array multiplies "
          "matrices and why that favours large batched matmuls.")


# ---------------- engine-seam mode (legacy) ----------------

async def run_session(engine, i: int, max_tokens: int) -> dict:
    from fasttalk_tpu.engine.engine import GenerationParams

    t0 = time.monotonic()
    ttft = None
    tokens = 0
    params = GenerationParams(temperature=0.7, top_k=40, top_p=0.9,
                              max_tokens=max_tokens)
    messages = [{"role": "user", "content": f"[session {i}] {PROMPT}"}]
    async for event in engine.generate(f"bench-req-{i}", f"bench-sess-{i}",
                                       messages, params):
        if event["type"] == "token":
            if ttft is None:
                ttft = (time.monotonic() - t0) * 1000.0
        elif event["type"] == "done":
            tokens = event["stats"]["tokens_generated"]
        elif event["type"] == "error":
            raise RuntimeError(f"generation failed: {event}")
    return {"tokens": tokens, "ttft_ms": ttft or 0.0,
            "wall_s": time.monotonic() - t0}


# ---------------- WebSocket mode (the real metric) ----------------

async def ws_session(http, i: int, max_tokens: int) -> dict:
    """One full protocol exchange; counts tokens that crossed the wire."""
    t0 = time.monotonic()
    ttft = None
    tokens = 0
    reported = 0
    async with http.ws_connect(f"ws://127.0.0.1:{PORT}/ws/llm") as ws:
        msg = json.loads((await ws.receive()).data)
        assert msg["type"] == "session_started", msg
        await ws.send_json({"type": "start_session",
                            "config": {"temperature": 0.7, "top_k": 40,
                                       "top_p": 0.9,
                                       "max_tokens": max_tokens,
                                       "ignore_eos": IGNORE_EOS}})
        msg = json.loads((await ws.receive()).data)
        assert msg["type"] == "session_configured", msg
        t0 = time.monotonic()
        await ws.send_json({"type": "user_message",
                            "text": f"[session {i}] {PROMPT}"})
        while True:
            frame = await ws.receive()
            msg = json.loads(frame.data)
            if msg["type"] == "token":
                if ttft is None:
                    ttft = (time.monotonic() - t0) * 1000.0
                tokens += 1
            elif msg["type"] == "response_complete":
                reported = msg["stats"]["tokens_generated"]
                break
            elif msg["type"] == "error":
                raise RuntimeError(f"generation failed: {msg}")
        await ws.send_json({"type": "end_session"})
        await ws.receive()  # session_ended
    return {"tokens": reported or tokens, "ttft_ms": ttft or 0.0,
            "wall_s": time.monotonic() - t0}


async def bench_ws(cfg) -> dict:
    import aiohttp
    from aiohttp import web

    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.serving.launcher import build_agent
    from fasttalk_tpu.serving.server import WebSocketLLMServer

    t0 = time.monotonic()
    engine = build_engine(cfg)
    log(f"engine built in {time.monotonic() - t0:.1f}s; warming up...")
    t1 = time.monotonic()
    engine.warmup(cfg.warmup)
    engine.start()
    log(f"warmup done in {time.monotonic() - t1:.1f}s")
    server = WebSocketLLMServer(cfg, engine, build_agent(cfg, engine))
    runner = web.AppRunner(server.app)
    await runner.setup()
    await web.TCPSite(runner, "127.0.0.1", PORT).start()
    log(f"server up on :{PORT} "
        f"(engine+warmup {time.monotonic() - t0:.1f}s total)")

    try:
        async with aiohttp.ClientSession() as http:
            # Warmup traffic: compile every shape the measurement hits
            # (single path AND the full-batch burst path).
            log("protocol warmup...")
            t2 = time.monotonic()
            await ws_session(http, 990, 8)
            await asyncio.gather(*(ws_session(http, 900 + i, 8)
                                   for i in range(NUM_SESSIONS)))
            log(f"protocol warmup done in {time.monotonic() - t2:.1f}s")
            reset_slo_after_warmup()

            # Median of 3 measurement passes per phase: the relayed
            # chip attach's round-trip latency varies run to run
            # (observed 40→250 ms across sessions, docs/PROFILE_TTFT.md)
            # and a single pass measures relay weather as much as the
            # engine. Medians are still one warmup + real passes —
            # nothing is cherry-picked.
            singles = []
            for rep in range(3):
                s = await ws_session(http, 100 + rep, MAX_TOKENS)
                singles.append((s["tokens"] / s["wall_s"], s["ttft_ms"]))
                log(f"  1 session (pass {rep + 1}): "
                    f"{singles[-1][0]:.1f} tok/s, "
                    f"TTFT {singles[-1][1]:.0f}ms")
            single_tps = statistics.median(t for t, _ in singles)
            single_ttft = statistics.median(t for _, t in singles)

            aggs = []
            for rep in range(3):
                await asyncio.sleep(1)  # drain stale pipeline tails
                t3 = time.monotonic()
                results = await asyncio.gather(
                    *(ws_session(http, 1000 * rep + i, MAX_TOKENS)
                      for i in range(NUM_SESSIONS)))
                wall = time.monotonic() - t3
                total_tokens = sum(r["tokens"] for r in results)
                aggs.append((total_tokens / wall, statistics.median(
                    r["ttft_ms"] for r in results)))
                log(f"  {NUM_SESSIONS} sessions (pass {rep + 1}): "
                    f"{total_tokens} tok in {wall:.2f}s = "
                    f"{aggs[-1][0]:.1f} tok/s aggregate, "
                    f"p50 TTFT {aggs[-1][1]:.0f}ms")
            agg_tps = statistics.median(a for a, _ in aggs)
            p50_ttft = statistics.median(t for _, t in aggs)
            if os.environ.get("BENCH_DUMP_METRICS"):
                from fasttalk_tpu.utils.metrics import get_metrics

                d = get_metrics().to_dict()
                for k in ("engine_prefill_ms", "engine_decode_wait_ms",
                          "engine_ttft_ms"):
                    log(f"  METRIC {k}: {d.get(k)}")
    finally:
        await runner.cleanup()
        engine.shutdown()

    return {"single_tps": single_tps, "single_ttft_ms": single_ttft,
            "agg_tps": agg_tps, "p50_ttft_ms": p50_ttft}


# ---------------- multiturn mode (KV host-offload tier) ----------------

async def _mt_turn(engine, i: int, messages: list[dict],
                   max_tokens: int) -> tuple[str, float]:
    """One engine-seam turn; returns (reply text, TTFT ms)."""
    from fasttalk_tpu.engine.engine import GenerationParams

    t0 = time.monotonic()
    ttft = None
    text = ""
    params = GenerationParams(temperature=0.7, top_k=40, top_p=0.9,
                              max_tokens=max_tokens)
    async for ev in engine.generate(
            f"mt-{i}-{len(messages)}", f"mt-sess-{i}", messages, params):
        if ev["type"] == "token":
            if ttft is None:
                ttft = (time.monotonic() - t0) * 1000.0
            text += ev["text"]
        elif ev["type"] == "error":
            raise RuntimeError(f"generation failed: {ev}")
    return text, ttft or 0.0


async def _mt_phase(cfg, sessions: int, turns: int,
                    max_tokens: int) -> dict:
    """One full multiturn scenario against a freshly built engine:
    ``sessions`` concurrent sessions each running ``turns`` turns under
    slot pressure (slots < sessions, so every wave evicts residents).
    Reports follow-up-turn (turn >= 2) TTFT and the pool's stats."""
    from fasttalk_tpu.engine.factory import build_engine

    engine = build_engine(cfg)
    engine.warmup(cfg.warmup)
    engine.start()
    followup_ttfts: list[float] = []
    try:
        histories: list[list[dict]] = [
            [{"role": "user", "content": f"[session {i}] {PROMPT}"}]
            for i in range(sessions)]
        # Warmup wave compiles the prefill/decode shapes the
        # measurement hits, on session ids outside the measured set.
        await asyncio.gather(*(
            _mt_turn(engine, 10_000 + i,
                     [{"role": "user", "content": f"[warm {i}] hi"}], 8)
            for i in range(sessions)))
        for i in range(sessions):
            engine.release_session(f"mt-sess-{10_000 + i}")
        reset_slo_after_warmup()
        for turn in range(turns):
            results = await asyncio.gather(*(
                _mt_turn(engine, i, histories[i], max_tokens)
                for i in range(sessions)))
            for i, (text, ttft) in enumerate(results):
                if turn >= 1:
                    followup_ttfts.append(ttft)
                histories[i].append({"role": "assistant", "content": text})
                histories[i].append(
                    {"role": "user",
                     "content": f"Continue, please (turn {turn + 2})."})
        kv = engine.get_stats().get("kv_host", {})
    finally:
        engine.shutdown()
    followup_ttfts.sort()
    n = len(followup_ttfts)
    return {
        "followup_turns": n,
        "followup_ttft_ms": {
            "p50": round(statistics.median(followup_ttfts), 1) if n else None,
            "p95": round(followup_ttfts[min(n - 1, int(0.95 * n))], 1)
            if n else None,
        },
        "restore_hit_ratio": kv.get("restore_hit_ratio"),
        "restored_total": kv.get("restored_total", 0),
        "parked_total": kv.get("parked_total", 0),
    }


def _mt_run_phase_subprocess(budget_mb: float) -> dict:
    """Run one multiturn phase in a CHILD process: two engines (one
    per phase) in a single process trip an XLA-CPU teardown crash that
    predates this bench mode, and per-phase processes are better
    isolation for a comparison anyway (fresh compile caches, no
    leaked-state asymmetry between the phases)."""
    import subprocess

    env = _child_env(BENCH_MT_PHASE="1",
                     BENCH_KV_BUDGET_MB=str(budget_mb))
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"multiturn phase (budget {budget_mb} MB) exited "
            f"{proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_multiturn() -> dict:
    """The KV host-offload scenario (docs/KVCACHE.md): N sessions x M
    turns with fewer slots than sessions, so every follow-up turn
    returns to an evicted session — measured twice, with the host pool
    off (KV_HOST_BUDGET_MB=0: follow-ups re-prefill their history) and
    on (follow-ups restore + delta-prefill). Each phase runs in its
    own subprocess."""
    sessions = int(os.environ.get("BENCH_MT_SESSIONS",
                                  str(NUM_SESSIONS)))
    turns = int(os.environ.get("BENCH_MT_TURNS", "3"))
    budget_mb = float(os.environ.get("BENCH_KV_BUDGET_MB", "256"))

    log(f"multiturn: {sessions} sessions x {turns} turns, "
        f"slots < sessions, pool off vs {budget_mb:.0f} MB...")
    log("--- phase 1/2: pool OFF (re-prefill path) ---")
    off = _mt_run_phase_subprocess(0.0)
    log(f"  off: follow-up TTFT p50/p95 "
        f"{off['followup_ttft_ms']['p50']}/"
        f"{off['followup_ttft_ms']['p95']} ms")
    log("--- phase 2/2: pool ON (park/restore path) ---")
    on = _mt_run_phase_subprocess(budget_mb)
    log(f"  on:  follow-up TTFT p50/p95 "
        f"{on['followup_ttft_ms']['p50']}/"
        f"{on['followup_ttft_ms']['p95']} ms, restore hit ratio "
        f"{on['restore_hit_ratio']}")
    speedup = None
    if off["followup_ttft_ms"]["p50"] and on["followup_ttft_ms"]["p50"]:
        speedup = round(off["followup_ttft_ms"]["p50"]
                        / on["followup_ttft_ms"]["p50"], 2)
    return {"sessions": sessions, "turns": turns,
            "kv_budget_mb": budget_mb, "off": off, "on": on,
            "followup_ttft_p50_speedup": speedup}


# ---------------- longctx mode (int8 KV-cache tier) ----------------

def _lc_long_prompt(eng, i: int, target: int) -> str:
    """A per-session-unique prompt calibrated to ~``target`` chat-
    template tokens on the engine's own tokenizer (the leading session
    tag keeps cross-session shared-prefix/intra-batch sharing out of
    the measurement)."""
    base = f"[session {i}] Summarise the following log. "
    filler = ("The quick brown fox jumps over the lazy dog and keeps "
              "running through the quiet valley at a steady pace. ")

    def toks(txt: str) -> int:
        return len(eng.tokenizer.apply_chat_template(
            [{"role": "user", "content": txt}]))

    n0 = toks(base + filler)
    per = max(1, toks(base + filler * 2) - n0)
    reps = 1 + max(0, (target - n0) // per)
    return base + filler * reps


async def _lc_phase(cfg, sessions: int, ctx_tokens: int,
                    max_tokens: int) -> dict:
    """One long-context capacity scenario against a freshly built
    engine: N sessions (N >> slots) each prefill a ~ctx_tokens prompt,
    get evicted and parked into the FIXED host budget; then every
    session returns for a follow-up (restore where its entry survived
    the budget). Reports how many sessions the budget actually held,
    the per-session parked bytes, restore latency, and decode tok/s —
    the int8-KV phase must hold ~2x the sessions and restore in ~half
    the time of the bf16 control on the SAME budget."""
    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.utils.metrics import get_metrics

    engine = build_engine(cfg)
    engine.warmup(cfg.warmup)
    engine.start()
    try:
        # Park wave: sequential admissions under slot pressure — each
        # new session evicts (and parks) an older one. Sequential on
        # purpose: batched admissions would interleave evictions and
        # blur the park accounting.
        prompts = [_lc_long_prompt(engine, i, ctx_tokens)
                   for i in range(sessions)]
        for i in range(sessions):
            r = await run_session_msgs(
                engine, f"lc-{i}", f"lc-sess-{i}",
                [{"role": "user", "content": prompts[i]}], max_tokens)
            assert r["tokens"] > 0
        # Let the copy thread drain (parks are async D2H fetches).
        pool = engine._kv_pool
        for _ in range(100):
            st = pool.stats()
            await asyncio.sleep(0.05)
            if pool.stats() == st:
                break
        st = pool.stats()
        entries = pool.snapshot()
        per_session = max((e["bytes"] for e in entries), default=0)
        # Force the restore decision for the latency measurement: on
        # fast-prefill setups (tiny CPU models) the cost model may
        # legitimately refuse bf16 restores — which is itself the
        # break-even shift the int8 tier buys, but this scenario must
        # measure the restore PATH both ways, so bias the EMAs until
        # every surviving entry restores.
        for _ in range(8):
            engine._kv_policy.note_copy(1 << 30, 0.001)
            engine._kv_policy.note_prefill(1, 1.0)
        # Restore wave: every session returns with its history + a
        # follow-up; sessions whose entries survived the budget restore
        # (half-the-bytes H2D on the int8 phase), the evicted ones
        # re-prefill. Most-recently-parked first: each admission parks
        # the occupant it evicts, and walking oldest-first would let
        # that churn LRU-evict every surviving entry moments before
        # its own turn — measuring pool thrash instead of restores.
        ttfts = []
        for i in reversed(range(sessions)):
            msgs = [{"role": "user", "content": prompts[i]},
                    {"role": "assistant", "content": "noted."},
                    {"role": "user", "content": "Continue, please."}]
            r = await run_session_msgs(engine, f"lc2-{i}",
                                       f"lc-sess-{i}", msgs, max_tokens)
            ttfts.append(r["ttft_ms"])
        st2 = engine.get_stats()["kv_host"]
        rh = get_metrics().histogram("kv_restore_ms")
        # Decode throughput check: a full batch of fresh short
        # sessions decoding concurrently — "within noise or better"
        # is the acceptance bar for the quantized phase.
        t0 = time.monotonic()
        results = await asyncio.gather(*(
            run_session_msgs(
                engine, f"lcd-{i}", f"lcd-sess-{i}",
                [{"role": "user", "content": f"[d{i}] {PROMPT}"}], 64)
            for i in range(cfg.decode_slots)))
        wall = time.monotonic() - t0
        tok_s = sum(r["tokens"] for r in results) / wall
        ttfts.sort()
    finally:
        engine.shutdown()
    return {
        "kv_quant": cfg.kv_quant,
        "budget_mb": cfg.kv_host_budget_mb,
        "parked_sessions": st["sessions"],
        "per_session_bytes": per_session,
        "per_session_mb": round(per_session / 2**20, 3),
        "park_rejected": st.get("rejected_total", 0),
        "restored_total": st2["restored_total"],
        "restore_p50_ms": round(rh.percentile(50), 2)
        if st2["restored_total"] else None,
        "followup_ttft_p50_ms": round(
            statistics.median(ttfts), 1) if ttfts else None,
        "decode_tok_s": round(tok_s, 2),
    }


async def run_session_msgs(engine, rid: str, sid: str,
                           messages: list[dict],
                           max_tokens: int) -> dict:
    """Engine-seam turn with explicit messages (longctx helper)."""
    from fasttalk_tpu.engine.engine import GenerationParams

    t0 = time.monotonic()
    ttft = None
    tokens = 0
    params = GenerationParams(temperature=0.7, top_k=40, top_p=0.9,
                              max_tokens=max_tokens)
    async for event in engine.generate(rid, sid, messages, params):
        if event["type"] == "token":
            if ttft is None:
                ttft = (time.monotonic() - t0) * 1000.0
            tokens += len(event["text"])
        elif event["type"] == "done":
            tokens = event["stats"]["tokens_generated"]
        elif event["type"] == "error":
            raise RuntimeError(f"generation failed: {event}")
    return {"tokens": tokens, "ttft_ms": ttft or 0.0,
            "wall_s": time.monotonic() - t0}


def _lc_run_phase_subprocess(kv_quant: str) -> dict:
    """One longctx phase per child process (same isolation rationale as
    multiturn: two warmed engines in one process trip the XLA-CPU
    teardown crash, and fresh processes keep the comparison fair)."""
    import subprocess

    env = _child_env(BENCH_LC_PHASE=kv_quant)
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"longctx phase (kv_quant={kv_quant}) exited "
            f"{proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_longctx() -> dict:
    """The quantized-KV capacity scenario (docs/KVCACHE.md "Quantized
    tier"): long-context sessions parked into a FIXED KV_HOST_BUDGET_MB,
    int8 KV vs the bf16 control — parked-session capacity per budget,
    restore-latency p50 both ways, and decode tok/s (must be within
    noise or better)."""
    from fasttalk_tpu.models.configs import get_model_config

    ctx = int(os.environ.get("BENCH_LC_CTX", "384"))
    sessions = int(os.environ.get("BENCH_LC_SESSIONS", "8"))
    m = get_model_config(MODEL)
    # The parked bucket every session lands in (kvcache/offload.py
    # kv_bucket): prompt + generation rounded up to a power of two.
    bucket = 1 << (ctx + 96 - 1).bit_length()
    bf16_entry_mb = 2 * m.num_layers * bucket * m.num_kv_heads \
        * m.head_dim * 2 / 2**20
    # Budget holds ~3.5 bf16 entries → ~7 int8+scales entries: the
    # capacity headline is the measured ratio, not this sizing.
    budget_mb = float(os.environ.get("BENCH_LC_BUDGET_MB",
                                     str(round(3.5 * bf16_entry_mb,
                                               3))))
    # The children inherit the PARENT's resolved budget, so the
    # reported budget_mb can never diverge from what the phases ran.
    os.environ["BENCH_LC_BUDGET_MB"] = str(budget_mb)
    log(f"longctx: {sessions} sessions x ~{ctx} ctx tokens, bucket "
        f"{bucket}, fixed budget {budget_mb:.1f} MB "
        f"(bf16 entry ~{bf16_entry_mb:.1f} MB)...")
    log("--- phase 1/2: bf16 KV (control) ---")
    off = _lc_run_phase_subprocess("none")
    log(f"  bf16: {off['parked_sessions']} parked x "
        f"{off['per_session_mb']} MB, restore p50 "
        f"{off['restore_p50_ms']} ms, decode {off['decode_tok_s']} "
        f"tok/s")
    log("--- phase 2/2: int8 KV ---")
    on = _lc_run_phase_subprocess("int8")
    log(f"  int8: {on['parked_sessions']} parked x "
        f"{on['per_session_mb']} MB, restore p50 "
        f"{on['restore_p50_ms']} ms, decode {on['decode_tok_s']} "
        f"tok/s")
    cap_ratio = (round(on["parked_sessions"]
                       / off["parked_sessions"], 2)
                 if off["parked_sessions"] else None)
    restore_speedup = (round(off["restore_p50_ms"]
                             / on["restore_p50_ms"], 2)
                       if off["restore_p50_ms"] and on["restore_p50_ms"]
                       else None)
    tok_ratio = (round(on["decode_tok_s"] / off["decode_tok_s"], 3)
                 if off["decode_tok_s"] else None)
    return {"sessions": sessions, "ctx_tokens": ctx, "bucket": bucket,
            "budget_mb": budget_mb, "bf16": off, "int8": on,
            "parked_capacity_ratio": cap_ratio,
            "restore_p50_speedup": restore_speedup,
            "decode_tok_s_ratio": tok_ratio}


# ---------------- int4 mode (weight-tier capacity) ----------------

async def _i4_phase(cfg, max_tokens: int) -> dict:
    """One weight-tier phase against a freshly built engine: a warmup
    decode wave (XLA compile), then a measured full-batch decode wave.
    Reports the tier's RESIDENT weight bytes (what admission prices),
    the per-step STREAMED bytes (what the perf ledger records), and
    decode tok/s."""
    import jax

    from fasttalk_tpu.engine.factory import build_engine

    engine = build_engine(cfg)
    engine.warmup(cfg.warmup)
    engine.start()
    try:
        resident = int(sum(x.nbytes for x in
                           jax.tree_util.tree_leaves(engine.params)))

        async def wave(tag: str) -> float:
            t0 = time.monotonic()
            results = await asyncio.gather(*(
                run_session_msgs(
                    engine, f"i4-{tag}-{i}", f"i4-{tag}-sess-{i}",
                    [{"role": "user", "content": f"[{tag}{i}] {PROMPT}"}],
                    max_tokens)
                for i in range(cfg.decode_slots)))
            wall = time.monotonic() - t0
            return sum(r["tokens"] for r in results) / wall

        await wave("warm")
        tok_s = await wave("run")
    finally:
        engine.shutdown()
    return {
        "weight_quant": cfg.weight_quant,
        "resident_weight_bytes": resident,
        "resident_weight_mb": round(resident / 2**20, 3),
        "streamed_bytes_per_step": engine._weight_bytes_per_step,
        "decode_tok_s": round(tok_s, 2),
    }


def _i4_run_phase_subprocess(tier: str) -> dict:
    """One tier per child process (same isolation rationale as
    multiturn/longctx: two warmed engines in one process trip the
    XLA-CPU teardown crash, and fresh processes keep the tiers fair)."""
    import subprocess

    env = _child_env(BENCH_I4_PHASE=tier)
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"int4 phase (weight_quant={tier}) exited "
            f"{proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_int4() -> dict:
    """The weight-tier capacity scenario (docs/QUANTIZATION.md): price
    a FIXED device-HBM budget per tier with the factory's own
    admission math, then measure decode tok/s per tier in isolated
    child processes. The envelope is analytic ON PURPOSE — it is the
    exact formula check_hbm_budget admits sessions by, so the headline
    is the serving capacity the factory will actually grant, not a
    simulation of it."""
    from fasttalk_tpu.engine.factory import weight_bytes_by_tier
    from fasttalk_tpu.models.configs import get_model_config

    m = get_model_config(MODEL, os.environ.get("MODEL_PATH"))
    group = int(os.environ.get("WEIGHT_QUANT_GROUP", "128"))
    dsize = 2  # bf16 serving dtype
    tiers = weight_bytes_by_tier(m, dsize, tp=1, group=group)
    budget = float(os.environ.get(
        "BENCH_I4_BUDGET_MB",
        str(round(1.5 * tiers["off"] / 2**20, 3)))) * 2**20
    # bf16 KV bytes per resident token (K+V): the KV tier is held
    # fixed so the envelope isolates what the WEIGHT tier frees.
    kv_row = 2 * m.num_layers * m.num_kv_heads * m.head_dim * dsize
    envelope = {t: max(0, int(budget) - b) // kv_row
                for t, b in tiers.items()}
    log(f"int4: fixed HBM budget {budget / 2**20:.1f} MB, weight "
        f"bytes off={tiers['off'] / 2**20:.1f} / "
        f"int8={tiers['int8'] / 2**20:.1f} / "
        f"int4={tiers['int4'] / 2**20:.1f} MB (group {group}) -> "
        f"resident KV envelope {envelope['off']} / {envelope['int8']}"
        f" / {envelope['int4']} token-rows")
    phases = {}
    for i, tier in enumerate(("off", "int8", "int4")):
        log(f"--- phase {i + 1}/3: WEIGHT_QUANT={tier} ---")
        phases[tier] = _i4_run_phase_subprocess(tier)
        log(f"  {tier}: {phases[tier]['resident_weight_mb']} MB "
            f"resident, decode {phases[tier]['decode_tok_s']} tok/s")
    cap_ratio = (round(envelope["int4"] / envelope["off"], 2)
                 if envelope["off"] else None)
    tok_vs_int8 = (round(phases["int4"]["decode_tok_s"]
                         / phases["int8"]["decode_tok_s"], 3)
                   if phases["int8"]["decode_tok_s"] else None)
    return {"budget_mb": round(budget / 2**20, 3), "group": group,
            "weight_bytes": tiers, "kv_row_bytes": kv_row,
            "envelope_token_rows": envelope,
            "envelope_ratio_int4_vs_bf16": cap_ratio,
            "off": phases["off"], "int8": phases["int8"],
            "int4": phases["int4"],
            "decode_tok_s_int4_vs_int8": tok_vs_int8}


# ---------------- paged mode (block-table KV cache) ----------------

async def _pg_session(engine, rid: str, sid: str, messages: list[dict],
                      max_tokens: int) -> dict:
    """One admission-wave turn that RETURNS a shed instead of raising:
    block-pool exhaustion rejections (code kv_blocks_exhausted, with
    retry_after) are a measured outcome of this scenario, not a bench
    failure."""
    from fasttalk_tpu.engine.engine import GenerationParams

    tokens = 0
    params = GenerationParams(temperature=0.7, top_k=40, top_p=0.9,
                              max_tokens=max_tokens)
    async for event in engine.generate(rid, sid, messages, params):
        if event["type"] == "token":
            tokens += 1
        elif event["type"] == "done":
            tokens = event["stats"]["tokens_generated"]
        elif event["type"] == "error":
            return {"tokens": tokens, "shed": True,
                    "code": event.get("code")}
    return {"tokens": tokens, "shed": False, "code": None}


async def _pg_admission_phase(cfg, sessions: int, contexts: list[int],
                              max_tokens: int) -> dict:
    """The fixed-HBM-budget admission scenario, one layout per child
    process: a MIXED-context fleet (the 512–32k production mix scaled
    to the bench max_len) submits concurrently and the phase reports
    how many sessions the layout held resident AT ONCE (peak
    concurrent decodes — the dense layout is hard-capped at
    rows_budget / max_len slots however short the prompts are), plus
    sheds, wall time, and — on the paged phase — the block pool's
    aliased-prefix savings from a shared-system-prompt wave."""
    from fasttalk_tpu.engine.factory import build_engine

    engine = build_engine(cfg)
    engine.warmup(cfg.warmup)
    engine.start()
    try:
        prompts = [_lc_long_prompt(engine, i, ctx)
                   for i, ctx in enumerate(contexts)]
        peak = {"running": 0}
        stop = asyncio.Event()

        async def sampler():
            while not stop.is_set():
                st = engine.get_stats()
                peak["running"] = max(peak["running"], st["running"])
                await asyncio.sleep(0.02)

        samp = asyncio.ensure_future(sampler())
        t0 = time.monotonic()
        results = await asyncio.gather(*(
            _pg_session(engine, f"pg-{i}", f"pg-sess-{i}",
                        [{"role": "user", "content": prompts[i]}],
                        max_tokens)
            for i in range(len(contexts))))
        wall = time.monotonic() - t0
        stop.set()
        await samp
        shed = sum(1 for r in results if r["shed"])
        out = {
            "kv_layout": cfg.kv_layout,
            "slots": cfg.decode_slots,
            "sessions": len(contexts),
            "completed": len(contexts) - shed,
            "shed": shed,
            "peak_concurrent": peak["running"],
            "wall_s": round(wall, 2),
            "tokens": sum(r["tokens"] for r in results),
        }
        if cfg.kv_layout == "paged":
            # Aliased-prefix savings: fresh sessions sharing one long
            # system prompt must stamp by refcount aliasing (zero KV
            # row copies beyond the COW tail block).
            sys_prompt = _lc_long_prompt(engine, 999, 256)
            for j in range(3):
                r = await _pg_session(
                    engine, f"pga-{j}", f"pga-sess-{j}",
                    [{"role": "system", "content": sys_prompt},
                     {"role": "user", "content": f"hello #{j}"}],
                    max_tokens)
                assert not r["shed"], r
            bl = engine.get_stats()["kv_blocks"]
            bs = bl["block_size"]
            out["blocks"] = {k: bl[k] for k in
                            ("total", "in_use", "aliased",
                             "alias_events", "cow_copies",
                             "fragmentation")}
            # Rows the aliased blocks would otherwise hold as copies.
            out["alias_saved_rows"] = bl["aliased"] * bs
    finally:
        engine.shutdown()
    return out


async def _pg_tput_phase(cfg, max_tokens: int) -> dict:
    """Short-context decode throughput at IDENTICAL slot count and a
    dense-equivalent pool: isolates the paged gather/scatter overhead
    (acceptance bar: within 10% of the dense control)."""
    from fasttalk_tpu.engine.factory import build_engine

    engine = build_engine(cfg)
    engine.warmup(cfg.warmup)
    engine.start()
    try:
        # Warmup wave compiles the shapes the measurement hits.
        await asyncio.gather(*(
            run_session_msgs(
                engine, f"pgw-{i}", f"pgw-sess-{i}",
                [{"role": "user", "content": f"[w{i}] hi"}], 8)
            for i in range(cfg.decode_slots)))
        t0 = time.monotonic()
        results = await asyncio.gather(*(
            run_session_msgs(
                engine, f"pgt-{i}", f"pgt-sess-{i}",
                [{"role": "user", "content": f"[d{i}] {PROMPT}"}],
                max_tokens)
            for i in range(cfg.decode_slots)))
        wall = time.monotonic() - t0
    finally:
        engine.shutdown()
    return {"kv_layout": cfg.kv_layout,
            "tok_s": round(sum(r["tokens"] for r in results) / wall, 2)}


def _pg_run_phase_subprocess(phase: str, layout: str) -> dict:
    """One paged phase per child process (same isolation rationale as
    multiturn/longctx: two warmed engines in one process trip the
    XLA-CPU teardown crash, and fresh processes keep the layouts'
    compile caches and heap symmetric)."""
    import subprocess

    env = _child_env(BENCH_PG_PHASE=phase, BENCH_PG_LAYOUT=layout)
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"paged phase ({phase}/{layout}) exited "
                           f"{proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def _pg_mixed_contexts(sessions: int, max_len: int) -> list[int]:
    """The production 512–32k context mix scaled into the bench
    max_len: geometric spread from max_len/32 up to max_len/2."""
    lo, hi = max(32, max_len // 32), max_len // 2
    step = (hi / lo) ** (1.0 / max(1, sessions - 1))
    return [min(hi, int(lo * step ** i)) for i in range(sessions)]


def bench_paged() -> dict:
    """The paged-KV capacity scenario (docs/KVCACHE.md "Paged tier"):
    a FIXED KV-row budget serves a mixed-context fleet under both
    layouts — dense affords only budget/max_len slots (admission
    priced at worst-case context), paged holds sessions by blocks in
    use — plus a same-slot-count short-context throughput pair bounding
    the gather/scatter overhead, and the aliased-prefix HBM savings."""
    sessions = int(os.environ.get("BENCH_PG_SESSIONS", "8"))
    max_len = int(os.environ.get("BENCH_PG_MAX_LEN", "2048"))
    rows = int(os.environ.get("BENCH_PG_KV_ROWS", "6144"))
    bs = int(os.environ.get("KV_BLOCK_SIZE", "16"))
    contexts = _pg_mixed_contexts(sessions, max_len)
    dense_slots = max(1, rows // max_len)
    log(f"paged: {sessions} sessions, contexts {contexts} on a fixed "
        f"{rows}-row KV budget (dense affords {dense_slots} x "
        f"{max_len} slots; paged {rows // bs} x {bs}-token blocks)...")
    log("--- phase 1/4: admission, dense control ---")
    d_adm = _pg_run_phase_subprocess("admission", "dense")
    log(f"  dense: peak {d_adm['peak_concurrent']} concurrent, "
        f"{d_adm['completed']}/{d_adm['sessions']} done in "
        f"{d_adm['wall_s']} s")
    log("--- phase 2/4: admission, paged ---")
    p_adm = _pg_run_phase_subprocess("admission", "paged")
    log(f"  paged: peak {p_adm['peak_concurrent']} concurrent, "
        f"{p_adm['completed']}/{p_adm['sessions']} done in "
        f"{p_adm['wall_s']} s, aliased {p_adm['blocks']['aliased']} "
        f"blocks ({p_adm['alias_saved_rows']} rows saved)")
    log("--- phase 3/4: throughput, dense control ---")
    d_tp = _pg_run_phase_subprocess("tput", "dense")
    log("--- phase 4/4: throughput, paged ---")
    p_tp = _pg_run_phase_subprocess("tput", "paged")
    log(f"  decode tok/s dense {d_tp['tok_s']} vs paged "
        f"{p_tp['tok_s']}")
    ratio = (round(p_adm["peak_concurrent"]
                   / d_adm["peak_concurrent"], 2)
             if d_adm["peak_concurrent"] else None)
    tok_ratio = (round(p_tp["tok_s"] / d_tp["tok_s"], 3)
                 if d_tp["tok_s"] else None)
    return {"sessions": sessions, "contexts": contexts,
            "kv_rows_budget": rows, "max_len": max_len,
            "block_size": bs, "dense_slots": dense_slots,
            "admission": {"dense": d_adm, "paged": p_adm},
            "concurrent_ratio": ratio,
            "alias_saved_rows": p_adm["alias_saved_rows"],
            "throughput": {"dense_tok_s": d_tp["tok_s"],
                           "paged_tok_s": p_tp["tok_s"],
                           "ratio": tok_ratio}}


# ---------------- radix mode (automatic prefix cache) ----------------

async def _rx_turn(engine, sid: str, messages: list[dict],
                   max_tokens: int) -> tuple[str, float]:
    """One agent turn under a FRESH session id, released as soon as it
    finishes — the stateless-proxy agent pattern: no session affinity,
    so nothing resident can serve the transcript prefix next turn.
    Returns (reply text, TTFT ms)."""
    from fasttalk_tpu.engine.engine import GenerationParams

    t0 = time.monotonic()
    ttft = None
    text = ""
    params = GenerationParams(temperature=0.7, top_k=40, top_p=0.9,
                              max_tokens=max_tokens)
    async for ev in engine.generate(f"req-{sid}", sid, messages,
                                    params):
        if ev["type"] == "token":
            if ttft is None:
                ttft = (time.monotonic() - t0) * 1000.0
            text += ev["text"]
        elif ev["type"] == "error":
            raise RuntimeError(f"generation failed: {ev}")
    engine.release_session(sid)
    return text, ttft or 0.0


async def _rx_phase(cfg, agents: int, turns: int,
                    max_tokens: int) -> dict:
    """One radix phase: ``agents`` concurrent agent transcripts, each
    re-submitted in full every turn. With the tree on, turn N should
    alias everything up to turn N-1 and prefill only the delta; off,
    every turn re-prefills the whole transcript. Reports follow-up
    (turn >= 2) TTFT and the tree's counters."""
    from fasttalk_tpu.engine.factory import build_engine

    engine = build_engine(cfg)
    engine.warmup(cfg.warmup)
    engine.start()
    followup_ttfts: list[float] = []
    try:
        histories: list[list[dict]] = [
            [{"role": "user", "content": f"[agent {i}] {PROMPT}"}]
            for i in range(agents)]
        # Warmup wave compiles the prefill/decode shapes the
        # measurement hits, on session ids outside the measured set.
        await asyncio.gather(*(
            _rx_turn(engine, f"rxw-{i}",
                     [{"role": "user", "content": f"[warm {i}] hi"}], 8)
            for i in range(agents)))
        reset_slo_after_warmup()
        for turn in range(turns):
            results = await asyncio.gather(*(
                _rx_turn(engine, f"rx-{i}-t{turn}", histories[i],
                         max_tokens)
                for i in range(agents)))
            for i, (text, ttft) in enumerate(results):
                if turn >= 1:
                    followup_ttfts.append(ttft)
                histories[i].append(
                    {"role": "assistant", "content": text})
                histories[i].append(
                    {"role": "user",
                     "content": f"Next step, please (turn "
                                f"{turn + 2})."})
        radix = engine.get_stats().get("kv_radix", {})
    finally:
        engine.shutdown()
    followup_ttfts.sort()
    n = len(followup_ttfts)
    return {
        "followup_turns": n,
        "followup_ttft_ms": {
            "p50": round(statistics.median(followup_ttfts), 1)
            if n else None,
            "p95": round(followup_ttfts[min(n - 1, int(0.95 * n))], 1)
            if n else None,
        },
        "radix": radix,
    }


def _rx_run_phase_subprocess(phase: str) -> dict:
    """One radix phase per child process (same isolation rationale as
    multiturn/longctx: two warmed engines in one process trip the
    XLA-CPU teardown crash, and fresh processes keep the phases'
    compile caches and heap symmetric)."""
    import subprocess

    env = _child_env(BENCH_RX_PHASE=phase)
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"radix phase ({phase}) exited "
                           f"{proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_radix() -> dict:
    """The automatic-prefix-cache scenario (docs/KVCACHE.md "Automatic
    prefix cache"): a growing agent transcript re-submitted every turn
    under fresh session ids, measured radix off (every turn re-
    prefills the whole history) and on (turn N aliases the cached
    chain and prefills only the delta). Each phase runs in its own
    subprocess."""
    agents = int(os.environ.get("BENCH_RX_AGENTS", "4"))
    turns = int(os.environ.get("BENCH_RX_TURNS", "4"))

    log(f"radix: {agents} agents x {turns} turns, fresh session id "
        f"per turn, KV_RADIX_ENABLED off vs on...")
    log("--- phase 1/2: radix OFF (re-prefill path) ---")
    off = _rx_run_phase_subprocess("off")
    log(f"  off: follow-up TTFT p50/p95 "
        f"{off['followup_ttft_ms']['p50']}/"
        f"{off['followup_ttft_ms']['p95']} ms")
    log("--- phase 2/2: radix ON (alias + delta-prefill path) ---")
    on = _rx_run_phase_subprocess("on")
    rx = on.get("radix", {})
    log(f"  on:  follow-up TTFT p50/p95 "
        f"{on['followup_ttft_ms']['p50']}/"
        f"{on['followup_ttft_ms']['p95']} ms, hit rate "
        f"{rx.get('hit_rate')}, bytes saved {rx.get('bytes_saved')}")
    speedup = None
    if off["followup_ttft_ms"]["p50"] and on["followup_ttft_ms"]["p50"]:
        speedup = round(off["followup_ttft_ms"]["p50"]
                        / on["followup_ttft_ms"]["p50"], 2)
    return {"agents": agents, "turns": turns, "off": off, "on": on,
            "followup_ttft_p50_speedup": speedup,
            "hit_rate": rx.get("hit_rate"),
            "hit_tokens": rx.get("hit_tokens"),
            "bytes_saved": rx.get("bytes_saved")}


# ---------------- roofline mode (decode attribution sweep) -------------

# The sweep grid: every decode configuration the compat matrix serves,
# as kv_quant:kv_layout:kernel triples. Overridable so a TPU run can
# focus (BENCH_RF_CONFIGS=int8:paged:pallas) and the CPU smoke can
# stay short.
_RF_ALL_CONFIGS = ("none:dense:xla,int8:dense:xla,"
                   "none:dense:pallas,int8:dense:pallas,"
                   "none:paged:xla,int8:paged:xla,"
                   "none:paged:pallas,int8:paged:pallas")


async def _rf_phase(cfg, max_tokens: int) -> dict:
    """One roofline cell: decode at full slot occupancy under one
    (kv_quant x kv_layout x kernel x steps_per_call) configuration,
    then read the perf ledger's attribution over the measured window
    so tok/s never travels without its decomposition
    (docs/ROOFLINE.md)."""
    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.observability.perf import get_perf

    engine = build_engine(cfg)
    engine.warmup(cfg.warmup)
    engine.start()
    try:
        # Warmup wave compiles the shapes the measurement hits.
        await asyncio.gather(*(
            run_session_msgs(
                engine, f"rfw-{i}", f"rfw-sess-{i}",
                [{"role": "user", "content": f"[w{i}] hi"}], 8)
            for i in range(cfg.decode_slots)))
        t0 = time.monotonic()
        results = await asyncio.gather(*(
            run_session_msgs(
                engine, f"rf-{i}", f"rf-sess-{i}",
                [{"role": "user", "content": f"[d{i}] {PROMPT}"}],
                max_tokens)
            for i in range(cfg.decode_slots)))
        wall = time.monotonic() - t0
        perf = get_perf().summary()
    finally:
        engine.shutdown()
    toks = sum(r["tokens"] for r in results)
    return {"kv_quant": cfg.kv_quant,
            "kv_layout": cfg.kv_layout,
            "kernel": perf.get("attention_kernel"),
            "steps_per_call": cfg.decode_steps_per_call,
            "slots": cfg.decode_slots,
            "tok_s": round(toks / wall, 2),
            "perf": perf}


def _rf_run_phase_subprocess(kv_quant: str, layout: str, kernel: str,
                             steps: int) -> dict:
    """One roofline cell per child process (same isolation rationale
    as every other multi-engine bench mode: fresh XLA state per cell,
    and a fresh perf-ledger window so cells never read each other's
    step records)."""
    import subprocess

    env = _child_env(BENCH_RF_PHASE="1",
                     BENCH_RF_KV=kv_quant,
                     BENCH_RF_LAYOUT=layout,
                     BENCH_RF_KERNEL=kernel,
                     TPU_DECODE_STEPS=str(steps))
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"roofline cell ({kv_quant}/{layout}/{kernel}/steps="
            f"{steps}) exited {proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_roofline() -> dict:
    """BENCH_MODE=roofline (docs/ROOFLINE.md): the measured-vs-ceiling
    attribution sweep. Each cell of (kv_quant x kv_layout x kernel) x
    steps_per_call runs decode at full occupancy in its own process
    and reports tok/s NEXT TO the perf ledger's decomposition —
    device-busy/host-gap fractions, MFU, KV and weight read bandwidth,
    and the first-order HBM ceiling (frac_of_ceiling == hbm_bw_util).
    The steps_per_call axis is the fetch-cadence axis: one device call
    covers `steps` tokens per slot between host token fetches."""
    steps_list = [int(s) for s in os.environ.get(
        "BENCH_RF_STEPS", "8,32").split(",") if s.strip()]
    configs = [c.strip().split(":") for c in os.environ.get(
        "BENCH_RF_CONFIGS", _RF_ALL_CONFIGS).split(",") if c.strip()]
    rows = []
    n = len(configs) * len(steps_list)
    i = 0
    for kv_quant, layout, kernel in configs:
        for steps in steps_list:
            i += 1
            log(f"--- roofline cell {i}/{n}: kv={kv_quant} "
                f"layout={layout} kernel={kernel} steps={steps} ---")
            r = _rf_run_phase_subprocess(kv_quant, layout, kernel,
                                         steps)
            p = r["perf"]
            ceil = p.get("frac_of_ceiling")
            ceil_txt = ("n/a (no HBM peak for this device kind)"
                        if ceil is None else str(ceil))
            log(f"  {r['tok_s']} tok/s via {r['kernel']} | busy "
                f"{p.get('device_busy_frac')} gap "
                f"{p.get('host_gap_frac')} | mfu {p.get('mfu')} | "
                f"kv {p.get('kv_read_gbps')} GB/s | ceiling frac "
                f"{ceil_txt}")
            rows.append(r)
    best = max(rows, key=lambda r: r["tok_s"])
    return {"rows": rows,
            "best": {k: best[k] for k in
                     ("kv_quant", "kv_layout", "kernel",
                      "steps_per_call", "tok_s")},
            "best_frac_of_ceiling": best["perf"].get(
                "frac_of_ceiling")}


# ---------------- fleet mode (router scale-out) ----------------

async def _fleet_failover(http, router, handles, max_tokens) -> dict:
    """Failover-resume latency scenario: long sessions stream across
    the fleet, the most-loaded replica's engine is shut down mid-stream,
    and every affected session must resume on a survivor (a `resumed`
    frame, then tokens — never an error frame). Reports the kill→resumed
    and kill→next-token latencies of the affected sessions."""
    n = len(handles) * 2
    shared = [dict(tokens=0, resumed_ms=None, next_token_ms=None,
                   error=None, done=False) for _ in range(n)]
    state = {"kill_t": None}

    async def victim(i):
        got = shared[i]
        async with http.ws_connect(
                f"ws://127.0.0.1:{PORT}/ws/llm") as ws:
            json.loads((await ws.receive()).data)  # session_started
            await ws.send_json({
                "type": "start_session",
                "config": {"max_tokens": max_tokens * 4,
                           "ignore_eos": IGNORE_EOS}})
            await ws.receive()  # session_configured
            await ws.send_json({"type": "user_message",
                                "text": f"[failover {i}] {PROMPT}"})
            resumed = False
            while True:
                msg = json.loads((await ws.receive()).data)
                if msg["type"] == "token":
                    got["tokens"] += 1
                    if resumed and got["next_token_ms"] is None \
                            and state["kill_t"] is not None:
                        got["next_token_ms"] = (
                            time.monotonic() - state["kill_t"]) * 1000
                elif msg["type"] == "resumed":
                    resumed = True
                    if state["kill_t"] is not None:
                        got["resumed_ms"] = (
                            time.monotonic() - state["kill_t"]) * 1000
                elif msg["type"] == "response_complete":
                    got["done"] = True
                    return
                elif msg["type"] == "error":
                    got["error"] = msg.get("error")
                    return

    tasks = [asyncio.create_task(victim(i)) for i in range(n)]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:  # all sessions streaming?
        if all(v["tokens"] >= 2 for v in shared):
            break
        await asyncio.sleep(0.02)
    # Kill the replica carrying the most live streams.
    owners = [h for _, h in router._routes.values()]
    target = max(handles, key=owners.count)
    affected = owners.count(target)
    log(f"  killing {target.replica_id} with {affected} live streams...")
    state["kill_t"] = time.monotonic()
    await asyncio.get_running_loop().run_in_executor(
        None, target.engine.shutdown)
    await asyncio.gather(*tasks)
    # A failed-over stream must leave ONE stitched cross-replica trace
    # retrievable over the wire (docs/OBSERVABILITY.md "Fleet
    # tracing"): router + both replicas' spans, exactly one terminal
    # event however many replicas served the stream.
    from fasttalk_tpu.observability.trace import get_tracer
    stitched = None
    for t in reversed(get_tracer().completed()):
        if any(s.name == "resume" for s in t.spans):
            async with http.get(f"http://127.0.0.1:{PORT}"
                                f"/traces/{t.request_id}") as r:
                if r.status == 200:
                    stitched = (await r.json()).get("stitched")
            break
    errors = [v["error"] for v in shared if v["error"]]
    resumed = sorted(v["resumed_ms"] for v in shared
                     if v["resumed_ms"] is not None)
    next_tok = sorted(v["next_token_ms"] for v in shared
                      if v["next_token_ms"] is not None)
    out = {
        "sessions": n,
        "affected": affected,
        "resumed": len(resumed),
        "errors": len(errors),
        "resume_latency_ms": {
            "p50": round(statistics.median(resumed), 1) if resumed
            else None,
            "max": round(resumed[-1], 1) if resumed else None,
        },
        "next_token_after_kill_ms": {
            "p50": round(statistics.median(next_tok), 1) if next_tok
            else None,
        },
        "stitched_trace": {
            "resumed": stitched["resumed"],
            "terminal_events": stitched["terminal_events"],
            "components": stitched["components"],
            "n_spans": stitched["n_spans"],
        } if stitched is not None else None,
    }
    log(f"  failover: {len(resumed)}/{affected} resumed, "
        f"{len(errors)} errors, resume p50 "
        f"{out['resume_latency_ms']['p50']} ms")
    if stitched is not None:
        log(f"  stitched trace: {stitched['resumed']} resumed / "
            f"{stitched['terminal_events']} terminal across "
            f"components {stitched['components']}")
    return out


async def _fleet_phase(cfg, replicas: int, sessions: int,
                       max_tokens: int) -> dict:
    """One fleet scenario in THIS process: N in-proc replicas behind a
    FleetRouter behind the real WebSocket server; measure aggregate
    WS tok/s, then (fleets only) the failover-resume scenario."""
    import aiohttp
    from aiohttp import web

    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.router import FleetRouter, ReplicaHandle
    from fasttalk_tpu.serving.server import WebSocketLLMServer

    handles = []
    for i in range(replicas):
        t0 = time.monotonic()
        eng = build_engine(cfg)
        eng.warmup(cfg.warmup)
        # Tag each replica's spans so the failover scenario's stitched
        # trace attributes hops to the replica that served them.
        eng.set_trace_component(f"inproc-{i}")
        handles.append(ReplicaHandle(f"inproc-{i}", eng))
        log(f"  replica {i} built+warmed in "
            f"{time.monotonic() - t0:.1f}s")
    router = FleetRouter(handles, probe_interval_s=1.0)
    router.start()
    server = WebSocketLLMServer(cfg, router, None)
    runner = web.AppRunner(server.app)
    await runner.setup()
    await web.TCPSite(runner, "127.0.0.1", PORT).start()
    out: dict = {"replicas": replicas, "sessions": sessions}
    async with aiohttp.ClientSession() as http:
        log("  protocol warmup...")
        await asyncio.gather(*(ws_session(http, 900 + i, 8)
                               for i in range(sessions)))
        reset_slo_after_warmup()
        t0 = time.monotonic()
        results = await asyncio.gather(
            *(ws_session(http, i, max_tokens)
              for i in range(sessions)))
        wall = time.monotonic() - t0
        total = sum(r["tokens"] for r in results)
        out["agg_tps"] = round(total / wall, 2)
        out["p50_ttft_ms"] = round(statistics.median(
            r["ttft_ms"] for r in results), 1)
        log(f"  {replicas} replica(s): {total} tok in {wall:.2f}s = "
            f"{out['agg_tps']} tok/s aggregate")
        if replicas > 1:
            out["failover"] = await _fleet_failover(http, router,
                                                    handles, max_tokens)
    await runner.cleanup()
    # Deliberately NO engine shutdown: multiple warmed XLA-CPU engines
    # in one process trip a pre-existing teardown crash (see the
    # multiturn notes); the child prints its JSON and hard-exits.
    return out


def _fleet_run_phase_subprocess(replicas: int) -> dict:
    """Each fleet size runs in its own child process (fresh XLA state,
    no teardown-order hazards between phases)."""
    import subprocess

    env = _child_env(BENCH_FLEET_PHASE=str(replicas))
    # Two in-proc engines racing the shared persistent XLA compile
    # cache segfault the XLA-CPU client (observed deterministic);
    # disable it for BOTH phases so the comparison stays fair.
    env["TPU_COMPILE_CACHE"] = "off"
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"fleet phase ({replicas} replicas) exited "
                           f"{proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_fleet(replicas: int, sessions: int, slots: int) -> dict:
    """The scale-out scenario (docs/ROUTER.md): ``sessions`` concurrent
    WS sessions against 1 replica vs ``replicas`` replicas, each
    replica holding ``slots`` decode slots — the single-replica phase
    is slot-starved (sessions > slots), the fleet serves them all
    concurrently, so aggregate tok/s measures what scaling out buys.
    The fleet phase then kills its most-loaded replica mid-stream and
    reports failover-resume latency."""
    import os as _os

    cores = _os.cpu_count() or 1
    log(f"fleet: {sessions} sessions, {slots} slots/replica, "
        f"1 vs {replicas} replicas on {cores} core(s)...")
    if cores < 2:
        # In-proc CPU replicas share the host's cores: on ONE core a
        # compute-bound decode cannot aggregate faster than a single
        # replica (scale-out buys tok/s only with a core/chip per
        # replica) — the fleet's single-host win is then queue-wait/
        # TTFT, which the report carries alongside.
        log("  WARNING: 1 CPU core — fleet aggregate tok/s cannot "
            "exceed single-replica here; watch p50_ttft_speedup")
    log("--- phase 1/2: single replica ---")
    single = _fleet_run_phase_subprocess(1)
    log("--- phase 2/2: fleet ---")
    fleet = _fleet_run_phase_subprocess(replicas)
    speedup = (round(fleet["agg_tps"] / single["agg_tps"], 2)
               if single.get("agg_tps") else None)
    ttft_speedup = (round(single["p50_ttft_ms"] / fleet["p50_ttft_ms"],
                          2)
                    if fleet.get("p50_ttft_ms") else None)
    return {"sessions": sessions, "slots_per_replica": slots,
            "cores": cores, "single": single, "fleet": fleet,
            "agg_tps_speedup": speedup,
            "p50_ttft_speedup": ttft_speedup}


# ---- fleet fabric: migration-vs-reprefill + rolling restart --------

def _fleet_fabric_cfg(slots: int):
    """Two-replica fabric phases share one engine config: KV host pool
    on, fast idle parks, long context for meaningful prefill."""
    from fasttalk_tpu.utils.config import Config

    return Config(llm_provider="tpu", model_name=MODEL,
                  decode_slots=slots, max_model_len=2048,
                  default_context_window=2048, prefill_chunk=512,
                  dtype="bfloat16", port=PORT,
                  monitoring_port=PORT + 1, enable_agent=False,
                  kv_host_budget_mb=256.0, kv_park_idle_s=0.2,
                  kv_restore_min_tokens=32,
                  quantize=os.environ.get("BENCH_QUANTIZE", "int8"))


async def _fleet_migration_phase(cfg, migrate_on: bool,
                                 sessions: int) -> dict:
    """One side of the migration-vs-reprefill comparison, in THIS
    process: N long-context sessions run their first turn on replica 0
    and idle-park there; replica 0 is then drained (rolling-restart
    shape) and every follow-up turn is measured on replica 1. With
    migration ON the drain moves the parked KV, so follow-ups RESTORE;
    OFF reproduces the pre-fabric behaviour (drain releases, follow-ups
    re-prefill the transcript). Follow-up TTFT p50 is the headline."""
    from fasttalk_tpu.engine.engine import GenerationParams
    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.router import FleetRouter, ReplicaHandle

    engines = []
    for i in range(2):
        t0 = time.monotonic()
        eng = build_engine(cfg)
        eng.warmup(cfg.warmup)
        engines.append(eng)
        log(f"  replica {i} built+warmed in "
            f"{time.monotonic() - t0:.1f}s")
    handles = [ReplicaHandle(f"inproc-{i}", e)
               for i, e in enumerate(engines)]
    router = FleetRouter(handles, probe_interval_s=1.0,
                         migrate=migrate_on, migrate_timeout_s=60.0)
    router.start()
    long_prompt = " ".join(f"[{i}] {PROMPT}" for i in range(6))
    greedy = dict(temperature=0.0, top_k=1)

    async def turn(rid, sid, messages, max_tokens=24):
        t0 = time.monotonic()
        ttft = None
        text = []
        async for ev in router.generate(
                rid, sid, messages,
                GenerationParams(max_tokens=max_tokens,
                                 ignore_eos=IGNORE_EOS, **greedy)):
            if ev["type"] == "token":
                if ttft is None:
                    ttft = (time.monotonic() - t0) * 1000.0
                text.append(ev.get("text", ""))
            elif ev["type"] == "error":
                raise RuntimeError(f"bench turn failed: {ev}")
        return ttft or 0.0, "".join(text)

    # First turns, all pinned to replica 0 (the one we will drain).
    replies = {}
    for i in range(sessions):
        sid = f"mig-{i}"
        router.affinity.set(sid, "inproc-0")
        _, replies[sid] = await turn(
            f"t1-{i}", sid,
            [{"role": "user", "content": long_prompt}])
    # Wait for the idle parks (KV_PARK_IDLE_S=0.2 + the 1 Hz tick).
    deadline = time.monotonic() + 30
    pool = engines[0]._kv_pool
    while time.monotonic() < deadline and any(
            pool.parked_len(f"mig-{i}") == 0 for i in range(sessions)):
        await asyncio.sleep(0.05)
    parked = sum(1 for i in range(sessions)
                 if pool.parked_len(f"mig-{i}") > 0)
    summary = await asyncio.to_thread(router.drain_replica, "inproc-0")
    log(f"  drained inproc-0: parked={parked} "
        f"migrated_kv={summary['migrated_kv']} "
        f"released={summary['released']}")
    # Follow-up turns: placement now lands on replica 1.
    ttfts = []
    for i in range(sessions):
        sid = f"mig-{i}"
        msgs = [{"role": "user", "content": long_prompt},
                {"role": "assistant", "content": replies[sid]},
                {"role": "user", "content": "and a short follow-up"}]
        ttft, _ = await turn(f"t2-{i}", sid, msgs, max_tokens=8)
        ttfts.append(ttft)
    ttfts.sort()
    restored = engines[1].get_stats()["kv_host"]["restored_total"]
    return {
        "migrate": migrate_on,
        "sessions": sessions,
        "parked_before_drain": parked,
        "migrated_kv": summary["migrated_kv"],
        "released": summary["released"],
        "followups_restored": restored,
        "followup_ttft_ms": {
            "p50": round(statistics.median(ttfts), 1),
            "max": round(ttfts[-1], 1),
        },
        "migration_policy": router.kv_policy.stats(),
    }
    # Deliberately no engine shutdown (see _fleet_phase note); the
    # child prints its JSON and hard-exits.


async def _fleet_rolling_phase(cfg, n_replicas: int,
                               sessions: int) -> dict:
    """The rolling-restart drill, in THIS process: long streams run
    across the fleet while every replica in turn is drained, KILLED
    mid-stream, and REPLACED by a pre-warmed successor through the
    elastic membership hooks (the k8s rolling-update shape: the new
    pod joins, the old one never comes back). Acceptance: zero
    client-visible error frames — affected streams see ``resumed``
    events and finish normally."""
    from fasttalk_tpu.engine.engine import GenerationParams
    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.router import FleetRouter, ReplicaHandle

    engines = {}
    spares = []
    for i in range(n_replicas * 2):  # fleet + one successor each
        t0 = time.monotonic()
        eng = build_engine(cfg)
        eng.warmup(cfg.warmup)
        log(f"  engine {i} built+warmed in "
            f"{time.monotonic() - t0:.1f}s")
        if i < n_replicas:
            engines[f"inproc-{i}"] = eng
        else:
            eng.start()  # successors boot warm, ready to join
            spares.append(eng)
    handles = [ReplicaHandle(rid, e, dead_probes=1)
               for rid, e in engines.items()]
    router = FleetRouter(handles, probe_interval_s=0,
                         failover_retries=n_replicas)
    router.start()
    n_streams = n_replicas * 2
    frames = [[] for _ in range(n_streams)]
    greedy = dict(temperature=0.0, top_k=1)

    async def stream(i):
        async for ev in router.generate(
                f"roll-{i}", f"roll-s{i}",
                [{"role": "user", "content": f"[{i}] {PROMPT}"}],
                GenerationParams(max_tokens=1500, ignore_eos=IGNORE_EOS,
                                 **greedy)):
            frames[i].append(ev)

    tasks = [asyncio.create_task(stream(i)) for i in range(n_streams)]
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        if all(any(e["type"] == "token" for e in f) for f in frames):
            break
        await asyncio.sleep(0.02)
    rounds = []
    for i in range(n_replicas):
        rid = f"inproc-{i}"
        t0 = time.monotonic()
        await asyncio.to_thread(router.drain_replica, rid)
        await asyncio.to_thread(engines[rid].shutdown)  # hard kill
        router.probe_once()  # dead within one probe (dead_probes=1)
        await asyncio.sleep(0.3)  # let affected streams resume
        successor = ReplicaHandle(f"{rid}-new", spares[i],
                                  dead_probes=1)
        successor.probe_now()
        router.add_replica(successor)
        router.remove_replica(rid)
        rounds.append({
            "replica": rid, "successor": successor.replica_id,
            "round_s": round(time.monotonic() - t0, 2),
            "successor_state": successor.state,
        })
        log(f"  rolled {rid} -> {successor.replica_id} "
            f"({successor.state}) in {rounds[-1]['round_s']}s")
    await asyncio.gather(*tasks)
    errors = sum(1 for f in frames
                 for e in f if e["type"] == "error")
    resumed = sum(1 for f in frames
                  for e in f if e["type"] == "resumed")
    completed = sum(1 for f in frames if f and f[-1]["type"] == "done")
    return {
        "replicas": n_replicas,
        "streams": n_streams,
        "rounds": rounds,
        "error_frames": errors,
        "resumed_events": resumed,
        "completed": completed,
        "migrations": router.fleet_stats()["counters"]["migrations"],
    }


async def _fleet_disagg_phase(cfg, role_split: bool,
                              sessions: int) -> dict:
    """One side of the disaggregation comparison, in THIS process:
    decode streams hold their slots and stream tokens while
    ``sessions`` long-prompt requests arrive mid-decode. Role-split
    runs replica 0 as the prefill tier (deep queue, zero decode slots)
    and replica 1 as the decode tier — long prompts prefill on 0, hand
    their KV over the migration wire, and decode on 1 — so a decode
    step never sits behind a long prefill chunk in its own scheduler.
    The mixed control runs the SAME engines with no roles, so long
    prefills time-share with decoding slots. Decode inter-token p99 is
    the headline — the number disaggregation exists to protect
    (docs/ROUTER.md "Disaggregated prefill/decode")."""
    from dataclasses import replace as dc_replace

    from fasttalk_tpu.engine.engine import GenerationParams
    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.router import FleetRouter, ReplicaHandle

    roles = ("prefill", "decode") if role_split else ("mixed", "mixed")
    engines = []
    for i, role in enumerate(roles):
        # Mirror build_fleet: the prefill tier absorbs burst arrivals
        # in queue depth instead of slot pressure.
        ecfg = (dc_replace(cfg,
                           sched_queue_bound=4 * cfg.sched_queue_bound)
                if role == "prefill" else cfg)
        t0 = time.monotonic()
        eng = build_engine(ecfg)
        eng.warmup(ecfg.warmup)
        engines.append(eng)
        log(f"  replica {i} ({role}) built+warmed in "
            f"{time.monotonic() - t0:.1f}s")
    handles = [ReplicaHandle(f"inproc-{i}", e, role=r)
               for i, (e, r) in enumerate(zip(engines, roles))]
    router = FleetRouter(handles, probe_interval_s=1.0, migrate=True,
                         migrate_timeout_s=60.0,
                         disagg_prefill_min_tokens=128)
    router.start()
    # Long enough to clear the 128-token threshold under BOTH the
    # byte tokenizer (~1 token/char) and a BPE one (~4 chars/token).
    long_prompt = " ".join(f"[{i}] {PROMPT}" for i in range(9))
    greedy = dict(temperature=0.0, top_k=1)
    # Leave decode headroom for the handed-off long sessions so both
    # sides queue comparably; the decode streams are the ITL probes.
    # Their prompt must stay WELL below the handoff threshold in any
    # tokenization, or the probes would take the handoff themselves.
    n_decode = max(1, cfg.decode_slots // 2)
    stamps = [[] for _ in range(n_decode)]
    errors = []

    async def decode_stream(i):
        async for ev in router.generate(
                f"dec-{i}", f"dec-s{i}",
                [{"role": "user", "content": f"[{i}] Say more."}],
                GenerationParams(max_tokens=512, ignore_eos=IGNORE_EOS,
                                 **greedy)):
            if ev["type"] == "token":
                stamps[i].append(time.monotonic())
            elif ev["type"] == "error":
                errors.append(ev)

    async def long_turn(i):
        t0 = time.monotonic()
        ttft = None
        async for ev in router.generate(
                f"long-{i}", f"long-s{i}",
                [{"role": "user", "content": f"[{i}] {long_prompt}"}],
                GenerationParams(max_tokens=16, ignore_eos=IGNORE_EOS,
                                 **greedy)):
            if ev["type"] == "token" and ttft is None:
                ttft = (time.monotonic() - t0) * 1000.0
            elif ev["type"] == "error":
                errors.append(ev)
        return ttft

    dec_tasks = [asyncio.create_task(decode_stream(i))
                 for i in range(n_decode)]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        if all(s for s in stamps):
            break  # every ITL probe is decoding before the burst
        await asyncio.sleep(0.02)
    burst0 = time.monotonic()
    ttfts = await asyncio.gather(*[long_turn(i)
                                   for i in range(sessions)])
    burst1 = time.monotonic()
    for i in range(n_decode):  # probes outlived the burst — done
        router.cancel(f"dec-{i}")
    await asyncio.gather(*dec_tasks)

    # ITL only while the burst was in flight — that is the window the
    # split protects; before/after it both fleets decode undisturbed.
    gaps = sorted(g for s in stamps
                  for a, b in zip(s, s[1:])
                  if b >= burst0 and a <= burst1
                  for g in ((b - a) * 1000.0,))
    if not gaps:  # probes finished early: fall back to the full run
        gaps = sorted((b - a) * 1000.0 for s in stamps
                      for a, b in zip(s, s[1:]))
    ttfts = sorted(t for t in ttfts if t is not None)

    def pct(xs, q):
        return (round(xs[min(len(xs) - 1, int(q * len(xs)))], 1)
                if xs else None)

    ds = router.fleet_stats()["disagg"]
    return {
        "role_split": role_split,
        "decode_streams": n_decode,
        "long_sessions": sessions,
        "decode_itl_ms": {"p50": pct(gaps, 0.50),
                          "p99": pct(gaps, 0.99),
                          "max": round(gaps[-1], 1) if gaps else None},
        "long_ttft_ms": {"p50": pct(ttfts, 0.50),
                         "max": round(ttfts[-1], 1) if ttfts else None},
        "error_frames": len(errors),
        "handoffs": ds["handoffs"],
        "fallbacks": ds["fallbacks"],
        "bytes_per_token": ds["bytes_per_token"],
        "tiers": ds["tiers"],
    }
    # Deliberately no engine shutdown (see _fleet_phase note); the
    # child prints its JSON and hard-exits.


def bench_fleet_disagg() -> dict:
    """The disaggregation acceptance pair (docs/ROUTER.md): the same
    mid-decode long-prompt burst against a role-split fleet (prefill
    tier hands KV to the decode tier over the migration wire) and a
    mixed control — role-split must protect decode inter-token p99,
    with long-prompt TTFT inside the priced-migration budget and zero
    client-visible error frames on both sides."""
    log("--- disagg 1/2: role-split (prefill|decode tiers) ---")
    split = _fleet_fabric_subprocess("BENCH_FLEET_DISAGG", "split")
    log("--- disagg 2/2: mixed control (same engines, no roles) ---")
    mixed = _fleet_fabric_subprocess("BENCH_FLEET_DISAGG", "mixed")
    gain = None
    if split["decode_itl_ms"]["p99"] and mixed["decode_itl_ms"]["p99"]:
        gain = round(mixed["decode_itl_ms"]["p99"]
                     / split["decode_itl_ms"]["p99"], 2)
    log(f"  decode ITL p99: split {split['decode_itl_ms']['p99']} ms "
        f"vs mixed {mixed['decode_itl_ms']['p99']} ms ({gain}x); "
        f"handoffs={split['handoffs']} "
        f"fallbacks={split['fallbacks']}; TTFT p50 split "
        f"{split['long_ttft_ms']['p50']} vs mixed "
        f"{mixed['long_ttft_ms']['p50']} ms; error frames "
        f"{split['error_frames']}+{mixed['error_frames']}")
    return {"split": split, "mixed": mixed,
            "decode_itl_p99_gain": gain,
            "error_frames": split["error_frames"]
            + mixed["error_frames"]}


def _fleet_fabric_subprocess(env_key: str, env_val: str) -> dict:
    """Run one fabric phase in a child process (fresh XLA state — the
    same isolation discipline as every other multi-engine bench)."""
    import subprocess

    env = _child_env(**{env_key: env_val})
    env["TPU_COMPILE_CACHE"] = "off"
    # Fabric children always dispatch through the fleet branch, even
    # when the parent is the standalone BENCH_MODE=disagg headline.
    env["BENCH_MODE"] = "fleet"
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, stdout=subprocess.PIPE, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"fleet fabric phase {env_key}={env_val} "
                           f"exited {proc.returncode}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def bench_fleet_fabric(replicas: int, sessions: int) -> dict:
    """The fabric acceptance pair (docs/ROUTER.md): (1) drain-migrate
    vs drain-release follow-up TTFT on long sessions — migration must
    beat re-prefill; (2) a rolling restart of N replicas with zero
    client-visible error frames."""
    log("--- fabric 1/3: drain + follow-up, migration ON ---")
    mig = _fleet_fabric_subprocess("BENCH_FLEET_MIGRATE", "on")
    log("--- fabric 2/3: drain + follow-up, migration OFF "
        "(re-prefill) ---")
    pre = _fleet_fabric_subprocess("BENCH_FLEET_MIGRATE", "off")
    speedup = None
    if mig["followup_ttft_ms"]["p50"]:
        speedup = round(pre["followup_ttft_ms"]["p50"]
                        / mig["followup_ttft_ms"]["p50"], 2)
    log(f"  follow-up TTFT p50: migrate "
        f"{mig['followup_ttft_ms']['p50']} ms vs re-prefill "
        f"{pre['followup_ttft_ms']['p50']} ms ({speedup}x)")
    log(f"--- fabric 3/3: rolling restart of {replicas} replicas ---")
    roll = _fleet_fabric_subprocess("BENCH_FLEET_ROLLING",
                                    str(replicas))
    log(f"  rolling restart: {roll['error_frames']} error frames, "
        f"{roll['resumed_events']} resumed, "
        f"{roll['completed']}/{roll['streams']} streams completed")
    return {"migrate": mig, "reprefill": pre,
            "followup_ttft_speedup": speedup,
            "rolling_restart": roll}


# ---------------- overload mode (admission control) ----------------

async def bench_overload(cfg) -> dict:
    """Open-loop overload: arrivals above service capacity. Reports how
    the scheduler degrades — who was shed (immediately, with
    retry_after), who expired in the queue, and what queue wait the
    admitted requests actually paid."""
    from fasttalk_tpu.engine.engine import GenerationParams
    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.utils.errors import AdmissionRejected
    from fasttalk_tpu.utils.metrics import get_metrics

    arrival_s = float(os.environ.get("BENCH_ARRIVAL_MS", "25")) / 1000.0
    duration_s = float(os.environ.get("BENCH_OVERLOAD_S", "20"))
    deadline_s = float(os.environ.get("BENCH_DEADLINE_S", "2.0"))

    t0 = time.monotonic()
    engine = build_engine(cfg)
    log(f"engine built in {time.monotonic() - t0:.1f}s; warming up...")
    engine.warmup(cfg.warmup)
    engine.start()

    out = {"arrived": 0, "done": 0, "shed": 0, "expired": 0,
           "error": 0, "tokens": 0}
    max_depth = 0

    async def one(i: int) -> None:
        params = GenerationParams(temperature=0.7, top_k=40, top_p=0.9,
                                  max_tokens=MAX_TOKENS,
                                  deadline_s=deadline_s)
        sid = f"ov-s{i}"
        try:
            async for ev in engine.generate(
                    f"ov-{i}", sid,
                    [{"role": "user", "content": f"[{i}] {PROMPT}"}],
                    params):
                if ev["type"] == "done":
                    out["done"] += 1
                    out["tokens"] += ev["stats"]["tokens_generated"]
                elif ev["type"] == "error":
                    key = ("expired"
                           if ev.get("code") == "deadline_expired"
                           else "error")
                    out[key] += 1
        except AdmissionRejected as e:
            assert e.retry_after is not None  # shed always hints
            out["shed"] += 1
        finally:
            engine.release_session(sid)

    try:
        log("overload warmup (compile)...")
        await one(999_999)
        for k in out:
            out[k] = 0
        reset_slo_after_warmup()
        rate = 1.0 / arrival_s
        log(f"open loop: {rate:.0f} req/s for {duration_s:.0f}s, "
            f"deadline {deadline_s}s, queue bound "
            f"{cfg.sched_queue_bound}...")
        t1 = time.monotonic()
        tasks = []
        i = 0
        while time.monotonic() - t1 < duration_s:
            tasks.append(asyncio.create_task(one(i)))
            out["arrived"] += 1
            i += 1
            depth = engine.get_stats()["scheduler"]["depth"]
            max_depth = max(max_depth, depth)
            await asyncio.sleep(arrival_s)
        await asyncio.gather(*tasks)
        wall = time.monotonic() - t1
    finally:
        engine.shutdown()

    # SLO goodput (observability/slo.py): the fraction of completed
    # requests that met EVERY objective — the honest headline under
    # overload, where raw tok/s stays flat while admitted users wait.
    slo_goodput, slo_alert = slo_goodput_summary()
    qw = get_metrics().histogram("queue_wait_ms")
    arrived = max(1, out["arrived"])
    res = {
        "arrival_rate_rps": round(1.0 / arrival_s, 2),
        "duration_s": round(wall, 2),
        "queue_bound": cfg.sched_queue_bound,
        "max_queue_depth": max_depth,
        "arrived": out["arrived"],
        "admitted_done": out["done"],
        "shed": out["shed"],
        "expired": out["expired"],
        "errors": out["error"],
        "shed_rate": round(out["shed"] / arrived, 4),
        "expiry_rate": round(out["expired"] / arrived, 4),
        "goodput_tok_s": round(out["tokens"] / wall, 1),
        "slo_goodput": slo_goodput,
        "slo_alert": slo_alert,
        "queue_wait_ms": {"p50": round(qw.percentile(50), 1),
                          "p95": round(qw.percentile(95), 1),
                          "p99": round(qw.percentile(99), 1)},
    }
    log(f"  {res['arrived']} arrived: {res['admitted_done']} done, "
        f"{res['shed']} shed ({res['shed_rate']:.1%}), "
        f"{res['expired']} expired ({res['expiry_rate']:.1%}); "
        f"max depth {max_depth}/{cfg.sched_queue_bound}; "
        f"admitted queue-wait p50/p95/p99 "
        f"{res['queue_wait_ms']['p50']:.0f}/"
        f"{res['queue_wait_ms']['p95']:.0f}/"
        f"{res['queue_wait_ms']['p99']:.0f} ms; "
        f"goodput {res['goodput_tok_s']:.1f} tok/s; "
        f"SLO goodput {fmt_goodput(slo_goodput)} "
        f"(alert {res['slo_alert']})")
    if max_depth > cfg.sched_queue_bound:
        log(f"  WARNING: observed queue depth {max_depth} exceeded the "
            f"bound {cfg.sched_queue_bound}")
    return res


async def bench_structured(engine) -> dict:
    """BENCH_MODE=structured (docs/STRUCTURED.md): two questions.

    1. **Mask overhead** — constrained decode steps gather/unpack one
       packed bitmask row per slot per step inside the jitted sampler;
       target <5% tok/s cost. Measured with an unforced constraint
       (``[ab]{N}``: every step a choice, no jump-forward, no early
       accept) against an unconstrained ``ignore_eos`` control of the
       same length, single session, greedy.
    2. **Jump-forward savings** — a schema whose fixed punctuation and
       long property names compile to single-transition chains; same
       greedy document with jump-forward off vs on, reporting the
       forced-token fraction and the e2e delta.
    """
    from fasttalk_tpu.engine.engine import GenerationParams
    from fasttalk_tpu.utils.metrics import get_metrics

    n_tok = int(os.environ.get("BENCH_ST_TOKENS", "96"))
    greedy = dict(temperature=0.0, top_k=0, top_p=1.0)

    async def one(rid, params, prompt="measure"):
        t0 = time.monotonic()
        ttft = None
        text = ""
        stats = {}
        async for ev in engine.generate(rid, f"sess-{rid}",
                                        [{"role": "user",
                                          "content": prompt}], params):
            if ev["type"] == "token":
                if ttft is None:
                    ttft = (time.monotonic() - t0) * 1000.0
                text += ev["text"]
            elif ev["type"] == "done":
                stats = ev["stats"]
            elif ev["type"] == "error":
                raise RuntimeError(f"generation failed: {ev}")
        engine.release_session(f"sess-{rid}")
        return {"wall_s": time.monotonic() - t0, "ttft_ms": ttft or 0.0,
                "tokens": stats.get("tokens_generated", 0),
                "text": text}

    log("warmup (compiling plain + constrained decode shapes)...")
    await one("warm-plain", GenerationParams(max_tokens=8, **greedy))
    await one("warm-st", GenerationParams(
        max_tokens=8, **greedy,
        structured={"kind": "regex", "regex": "[ab]{512}"}))

    log("mask-overhead phase (unconstrained control vs [ab]{N})...")
    reps = int(os.environ.get("BENCH_ST_REPS", "3"))
    base_s, mask_s = [], []
    for i in range(reps):
        r = await one(f"base{i}", GenerationParams(
            max_tokens=n_tok, ignore_eos=True, **greedy))
        base_s.append(r["tokens"] / r["wall_s"])
        r = await one(f"mask{i}", GenerationParams(
            max_tokens=n_tok, **greedy,
            structured={"kind": "regex",
                        "regex": "[ab]{%d}" % (4 * n_tok)}))
        mask_s.append(r["tokens"] / r["wall_s"])
    base_tps = statistics.median(base_s)
    mask_tps = statistics.median(mask_s)
    overhead = 1.0 - mask_tps / base_tps

    log("jump-forward phase (forced-chain schema, off vs on)...")
    # Long single-transition runs: fixed punctuation + numeric
    # property names are forced for any tokenizer (digits are
    # single-byte tokens in every BPE's base alphabet).
    schema = {"type": "object", "properties": {
        "measurement_0123456789_a": {"enum": ["blue", "red"]},
        "measurement_0123456789_b": {"type": "boolean"},
        "measurement_0123456789_c": {"enum": [1, 2, 3]}}}
    sp = dict(structured={"kind": "json_schema", "schema": schema})
    jf_min, counter = engine._st_jf_min, get_metrics().counter(
        "structured_jump_forward_tokens_total")
    try:
        engine._st_jf_min = 0
        await one("jfw0", GenerationParams(max_tokens=256, **greedy,
                                           **sp))  # compile prefill
        offs = [await one(f"jf-off{i}", GenerationParams(
            max_tokens=256, **greedy, **sp)) for i in range(reps)]
        engine._st_jf_min = int(os.environ.get("BENCH_ST_JF_MIN", "4"))
        await one("jfw1", GenerationParams(max_tokens=256, **greedy,
                                           **sp))  # compile jump path
        before = counter.value
        ons = [await one(f"jf-on{i}", GenerationParams(
            max_tokens=256, **greedy, **sp)) for i in range(reps)]
        jumped = (counter.value - before) / reps
    finally:
        engine._st_jf_min = jf_min
    assert all(o["text"] == offs[0]["text"] for o in offs + ons), \
        "jump-forward changed the greedy document"
    off_ms = statistics.median(o["wall_s"] for o in offs) * 1000
    on_ms = statistics.median(o["wall_s"] for o in ons) * 1000
    doc_tokens = offs[0]["tokens"]
    res = {
        "unconstrained_tok_s": round(base_tps, 2),
        "constrained_tok_s": round(mask_tps, 2),
        "mask_overhead_frac": round(overhead, 4),
        "jump_forward": {
            "doc_tokens": doc_tokens,
            "forced_tokens": round(jumped, 1),
            "forced_fraction": round(jumped / max(1, doc_tokens), 3),
            "e2e_off_ms": round(off_ms, 1),
            "e2e_on_ms": round(on_ms, 1),
            "e2e_speedup": round(off_ms / on_ms, 2) if on_ms else None,
        },
        "fsm_compile_ms": get_metrics().histogram(
            "fsm_compile_ms").summary(),
    }
    log(f"  mask overhead {overhead:.1%} ({base_tps:.1f} -> "
        f"{mask_tps:.1f} tok/s); jump-forward forced "
        f"{res['jump_forward']['forced_fraction']:.0%} of "
        f"{doc_tokens} tokens, e2e {off_ms:.0f} -> {on_ms:.0f} ms "
        f"({res['jump_forward']['e2e_speedup']}x)")
    return res


# ---------------- chaos mode (docs/RESILIENCE.md) ----------------

async def _chaos_failover_drill(streams: int = 8,
                                delay_s: float = 0.004) -> dict:
    """Router failover recovery timing: N streams over two replicas,
    kill one mid-decode, measure kill->`resumed` latency per affected
    stream. FakeEngine-based on purpose — this measures the ROUTING
    layer's recovery deadline, not model throughput (the real-engine
    fleet is BENCH_MODE=fleet), so it runs in milliseconds and is
    device-independent."""
    from fasttalk_tpu.engine.engine import GenerationParams
    from fasttalk_tpu.engine.fake import FakeEngine
    from fasttalk_tpu.router import FleetRouter, ReplicaHandle
    from fasttalk_tpu.utils.errors import ErrorCategory, LLMServiceError

    class Mortal(FakeEngine):
        def __init__(self):
            super().__init__(reply="alpha beta gamma delta epsilon "
                             "zeta eta theta ", n_repeats=12,
                             delay_s=delay_s)
            self.dead = False

        def kill(self):
            self.dead = True
            self._started = False

        def check_connection(self):
            return not self.dead and self._started

        async def generate(self, rid, sid, messages, params):
            if self.dead:
                raise LLMServiceError(
                    "replica down", category=ErrorCategory.CONNECTION)
            async for ev in super().generate(rid, sid, messages,
                                             params):
                if self.dead:
                    raise LLMServiceError(
                        "replica died mid-stream",
                        category=ErrorCategory.CONNECTION)
                yield ev

    engines = [Mortal(), Mortal()]
    for e in engines:
        e.start()
    handles = [ReplicaHandle(f"r{i}", e, dead_probes=1)
               for i, e in enumerate(engines)]
    router = FleetRouter(handles, probe_interval_s=0,
                         failover_retries=2)
    router.start()
    kill_at: dict = {"t": None}
    resume_ms: list[float] = []
    errors = 0

    async def stream(i: int) -> None:
        nonlocal errors
        try:
            async for ev in router.generate(
                    f"chaos-req-{i}", f"chaos-sess-{i}",
                    [{"role": "user", "content": "hi"}],
                    GenerationParams(max_tokens=64, temperature=0.0,
                                     top_k=1)):
                if ev["type"] == "resumed" \
                        and kill_at["t"] is not None:
                    resume_ms.append(
                        (time.monotonic() - kill_at["t"]) * 1000)
                elif ev["type"] == "error":
                    errors += 1
        except Exception:
            errors += 1

    tasks = [asyncio.create_task(stream(i)) for i in range(streams)]
    await asyncio.sleep(delay_s * 8)  # streams underway on both
    kill_at["t"] = time.monotonic()
    engines[0].kill()
    await asyncio.gather(*tasks)
    affected = len({r["session_id"] for r in engines[0].requests_seen})
    router.shutdown()
    return {
        "streams": streams,
        "affected": affected,
        "resumed": len(resume_ms),
        "errors": errors,
        "resume_p50_ms": round(statistics.median(resume_ms), 2)
        if resume_ms else None,
    }


async def bench_chaos(engine) -> dict:
    """The failpoints-off CONTROL: does the fault-injection subsystem
    cost anything when FAULT_POINTS is unset? Interleaved phases —
    failpoints OFF vs ARMED-but-inert (a p=0 rule on the decode
    dispatch seam, so the registry lookup runs on every dispatch and
    never fires) — must agree within 1% tok/s. The MTTR and failover
    halves of BENCH_MODE=chaos live in _chaos_mttr_drill /
    _chaos_failover_drill (orchestrated by bench_chaos_main)."""
    from fasttalk_tpu.resilience import failpoints as fp

    log("warmup (compiling prefill + decode buckets)...")
    t0 = time.monotonic()
    await run_session(engine, 999, max_tokens=8)
    engine.release_session("bench-sess-999")
    await asyncio.gather(
        *(run_session(engine, 900 + i, max_tokens=8)
          for i in range(NUM_SESSIONS)))
    for i in range(NUM_SESSIONS):
        engine.release_session(f"bench-sess-{900 + i}")
    log(f"warmup done in {time.monotonic() - t0:.1f}s")
    reset_slo_after_warmup()

    async def tps_phase() -> float:
        # Several waves per phase: single-wave phases (~1 s on CPU
        # tiny) sit below the shared-box noise burst scale and swung
        # 2.5x between back-to-back identical runs; a phase must be
        # long enough to average over the bursts it cannot avoid.
        waves = int(os.environ.get("BENCH_CHAOS_WAVES", "3"))
        t0 = time.monotonic()
        tokens = 0
        for _ in range(waves):
            results = await asyncio.gather(
                *(run_session(engine, i, MAX_TOKENS)
                  for i in range(NUM_SESSIONS)))
            tokens += sum(r["tokens"] for r in results)
        wall = time.monotonic() - t0
        for i in range(NUM_SESSIONS):
            engine.release_session(f"bench-sess-{i}")
        return tokens / wall

    # (1) Control. Two noise sources dominate short CPU phases: the
    # client warms in over several runs (throughput climbs ~2x before
    # settling), and a shared box swings ±10-30% run to run. So:
    # warm until two consecutive phases agree within 5%, then measure
    # PAIRS — off and armed back to back, order alternating per pair
    # — and take the median of the pairwise armed/off ratios. Within
    # a pair (seconds apart) drift is small; the alternating order
    # cancels its direction; the median rejects outlier pairs. This
    # resolves a sub-1% effect where arm-wise medians or maxima of
    # the same phases swing several percent.
    log("control phases: failpoints off vs armed-inert (p=0)...")
    prev = await tps_phase()
    for _ in range(8):  # warm until stable
        cur = await tps_phase()
        if abs(cur - prev) / prev < 0.05:
            break
        prev = cur

    async def armed_phase() -> float:
        fp.activate("engine.decode.dispatch=error;p=0.0")
        try:
            return await tps_phase()
        finally:
            fp.clear()

    off_tps: list[float] = []
    armed_tps: list[float] = []
    ratios: list[float] = []
    for k in range(6):
        if k % 2 == 0:
            o = await tps_phase()
            a = await armed_phase()
        else:
            a = await armed_phase()
            o = await tps_phase()
        off_tps.append(o)
        armed_tps.append(a)
        ratios.append(a / o)
    tps_off = statistics.median(off_tps)
    tps_armed = statistics.median(armed_tps)
    delta = statistics.median(ratios) - 1.0
    log(f"  off {tps_off:.1f} tok/s vs armed-inert {tps_armed:.1f} "
        f"tok/s: delta {delta:+.2%} (target |delta| < 1%)")

    return {
        "control": {
            "off_tps": round(tps_off, 2),
            "armed_tps": round(tps_armed, 2),
            "delta_frac": round(delta, 4),
            "off_runs": [round(x, 2) for x in off_tps],
            "armed_runs": [round(x, 2) for x in armed_tps],
        },
    }


async def _chaos_mttr_drill(engine) -> dict:
    """One crash->restart MTTR drill (subprocess body): crash the
    engine thread under an injected crash_thread mid-decode,
    supervised-restart it, and time crash-detected -> restart-complete
    -> first post-restart token."""
    from fasttalk_tpu.resilience import failpoints as fp

    await run_session(engine, 999, max_tokens=8)  # compile warm
    engine.release_session("bench-sess-999")
    loop = asyncio.get_running_loop()
    victim = asyncio.create_task(run_session(engine, 700, 400))
    while not engine._running:
        await asyncio.sleep(0.005)
    fp.activate("engine.loop.tick=crash_thread;count=1")
    while engine.check_connection():
        await asyncio.sleep(0.005)
    fp.clear()
    t_dead = time.monotonic()
    ok = await loop.run_in_executor(None, engine.restart)
    assert ok, "supervised engine restart failed mid-bench"
    restart_ms = (time.monotonic() - t_dead) * 1000
    post = await run_session(engine, 800, max_tokens=8)
    try:
        await victim  # terminal internal_error from the crash
    except RuntimeError:
        pass
    return {"restart_ms": round(restart_ms, 1),
            "mttr_ms": round(restart_ms + post["ttft_ms"], 1)}


def _chaos_run_subprocess(phase: str) -> dict:
    """One chaos phase in its own interpreter (BENCH_CHAOS_PHASE=
    control|mttr). Subprocess isolation for the same reason as the
    multiturn/fleet phases: a worked engine's in-process teardown —
    and doubly a crash->restart cycle's abandoned dispatches — trips
    the pre-existing XLA-CPU client heap fragility that accelerator
    runtimes don't share. The parent therefore never builds an engine
    at all. One drill per process is also the honest MTTR shape:
    production restarts happen in a fresh process history, not after
    N prior crash cycles."""
    import subprocess

    env = _child_env(BENCH_CHAOS_PHASE=phase)
    last_err = ""
    for _attempt in range(2):  # native-runtime flakes get one retry
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                env=env, capture_output=True, text=True, timeout=900)
        except subprocess.TimeoutExpired:
            # A wedged child is the hang-class flake; it gets the
            # same retry a crashed one does.
            last_err = "child timed out after 900s"
            continue
        for line in reversed(proc.stdout.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        last_err = proc.stderr[-2000:]
    raise RuntimeError(
        f"chaos {phase} subprocess produced no JSON; stderr tail:\n"
        f"{last_err}")


def bench_chaos_main() -> dict:
    """BENCH_MODE=chaos orchestration: (1) the failpoints-off control,
    (2) three engine-restart MTTR drills — each subprocess-isolated —
    and (3) the router failover drill (FakeEngine fleet, in-proc)."""
    log("control phase (subprocess): failpoints off vs armed-inert...")
    control = _chaos_run_subprocess("control")
    log(f"  off {control['off_tps']} tok/s vs armed-inert "
        f"{control['armed_tps']} tok/s: delta "
        f"{control['delta_frac']:+.2%} (target |delta| < 1%)")

    log("engine-restart MTTR drills (subprocess-isolated)...")
    drills = []
    for k in range(3):
        d = _chaos_run_subprocess("mttr")
        drills.append(d)
        log(f"  drill {k + 1}: restart {d['restart_ms']:.0f} ms, "
            f"MTTR-to-first-token {d['mttr_ms']:.0f} ms")

    log("router failover drill (2 fake replicas, kill mid-decode)...")
    failover = asyncio.run(_chaos_failover_drill())
    log(f"  resumed {failover['resumed']}/{failover['affected']} "
        f"streams, {failover['errors']} errors, resume p50 "
        f"{failover['resume_p50_ms']} ms")

    return {
        "control": control,
        "restart_p50_ms": round(statistics.median(
            [d["restart_ms"] for d in drills]), 1),
        "mttr_p50_ms": round(statistics.median(
            [d["mttr_ms"] for d in drills]), 1),
        "mttr_runs_ms": [d["mttr_ms"] for d in drills],
        "failover": failover,
    }


# ---------------- profiler mode (sampler overhead control) -------------

async def bench_profiler(engine) -> dict:
    """The continuous-profiler zero-overhead control
    (docs/OBSERVABILITY.md "Continuous profiler and program
    attribution"): decode throughput with the host stack sampler OFF
    vs ON at PROF_HZ must agree within 1% — the contract that lets the
    sampler ship enabled in production. Same pairwise-interleaved
    design as the failpoints control (bench_chaos): warm until two
    consecutive phases agree, then take the median of back-to-back
    on/off ratios with alternating order (drift within a pair is
    small; alternation cancels its direction; the median rejects
    outlier pairs). The ON phases feed the host-gap cause
    decomposition, so the result also carries host_gap_causes and the
    per-program attribution next to the delta."""
    from fasttalk_tpu.observability import profiler as profmod
    from fasttalk_tpu.observability.perf import get_perf

    log("warmup (compiling prefill + decode buckets)...")
    t0 = time.monotonic()
    await run_session(engine, 999, max_tokens=8)
    engine.release_session("bench-sess-999")
    await asyncio.gather(
        *(run_session(engine, 900 + i, max_tokens=8)
          for i in range(NUM_SESSIONS)))
    for i in range(NUM_SESSIONS):
        engine.release_session(f"bench-sess-{900 + i}")
    log(f"warmup done in {time.monotonic() - t0:.1f}s")
    reset_slo_after_warmup()

    # This mode exists to measure the sampler, so it runs enabled
    # regardless of the ambient PROF_ENABLED — through the singleton,
    # so the perf ledger's host_gap_causes block sees its samples.
    os.environ["PROF_ENABLED"] = "true"
    profmod.reset_profiler()
    prof = profmod.get_profiler()

    async def tps_phase() -> float:
        # Several waves per phase (see bench_chaos.tps_phase for why).
        waves = int(os.environ.get("BENCH_PROF_WAVES", "3"))
        t0 = time.monotonic()
        tokens = 0
        for _ in range(waves):
            results = await asyncio.gather(
                *(run_session(engine, i, MAX_TOKENS)
                  for i in range(NUM_SESSIONS)))
            tokens += sum(r["tokens"] for r in results)
        wall = time.monotonic() - t0
        for i in range(NUM_SESSIONS):
            engine.release_session(f"bench-sess-{i}")
        return tokens / wall

    async def on_phase() -> float:
        prof.start()
        try:
            return await tps_phase()
        finally:
            prof.stop()

    log(f"control phases: sampler off vs on ({prof.hz:g} Hz)...")
    prev = await tps_phase()
    for _ in range(8):  # warm until stable
        cur = await tps_phase()
        if abs(cur - prev) / prev < 0.05:
            break
        prev = cur

    off_tps: list[float] = []
    on_tps: list[float] = []
    ratios: list[float] = []
    for k in range(6):
        if k % 2 == 0:
            o = await tps_phase()
            a = await on_phase()
        else:
            a = await on_phase()
            o = await tps_phase()
        off_tps.append(o)
        on_tps.append(a)
        ratios.append(a / o)
    tps_off = statistics.median(off_tps)
    tps_on = statistics.median(on_tps)
    delta = statistics.median(ratios) - 1.0
    log(f"  off {tps_off:.1f} tok/s vs sampling {tps_on:.1f} tok/s: "
        f"delta {delta:+.2%} (target |delta| < 1%)")

    rep = prof.report(top=5)
    perf = get_perf().summary()
    return {
        "control": {
            "off_tps": round(tps_off, 2),
            "on_tps": round(tps_on, 2),
            "delta_frac": round(delta, 4),
            "off_runs": [round(x, 2) for x in off_tps],
            "on_runs": [round(x, 2) for x in on_tps],
        },
        "sampler": {"hz": prof.hz, "samples": rep["samples"],
                    "errors": rep["errors"],
                    "dropped_stacks": rep["dropped_stacks"]},
        "host_gap_causes": perf.get("host_gap_causes"),
        "programs_top": perf.get("programs_top"),
    }


async def bench_engine(engine) -> dict:
    log("warmup (compiling prefill + decode buckets)...")
    t0 = time.monotonic()
    await run_session(engine, 999, max_tokens=8)
    engine.release_session("bench-sess-999")
    await asyncio.gather(
        *(run_session(engine, 900 + i, max_tokens=8)
          for i in range(NUM_SESSIONS)))
    for i in range(NUM_SESSIONS):
        engine.release_session(f"bench-sess-{900 + i}")
    log(f"warmup done in {time.monotonic() - t0:.1f}s")
    reset_slo_after_warmup()

    log("single-session run...")
    single = await run_session(engine, 0, MAX_TOKENS)
    engine.release_session("bench-sess-0")
    single_tps = single["tokens"] / single["wall_s"]
    log(f"  1 session: {single['tokens']} tok in {single['wall_s']:.2f}s "
        f"= {single_tps:.1f} tok/s, TTFT {single['ttft_ms']:.0f}ms")

    log(f"{NUM_SESSIONS} concurrent sessions...")
    t0 = time.monotonic()
    results = await asyncio.gather(
        *(run_session(engine, i, MAX_TOKENS) for i in range(NUM_SESSIONS)))
    wall = time.monotonic() - t0
    for i in range(NUM_SESSIONS):
        engine.release_session(f"bench-sess-{i}")
    total_tokens = sum(r["tokens"] for r in results)
    agg_tps = total_tokens / wall
    p50_ttft = statistics.median(r["ttft_ms"] for r in results)
    log(f"  {NUM_SESSIONS} sessions: {total_tokens} tok in {wall:.2f}s "
        f"= {agg_tps:.1f} tok/s aggregate, p50 TTFT {p50_ttft:.0f}ms")

    return {"single_tps": single_tps, "single_ttft_ms": single["ttft_ms"],
            "agg_tps": agg_tps, "p50_ttft_ms": p50_ttft}


def main() -> None:
    import jax

    log(f"jax devices: {jax.devices()}")

    from fasttalk_tpu.utils.config import Config

    extra = {}
    if MODE == "overload":
        # Small bound + short deadline so the open-loop scenario
        # actually exercises shed AND expiry within the run.
        extra = dict(
            sched_queue_bound=int(os.environ.get("BENCH_QUEUE_BOUND",
                                                 "32")),
            sched_default_deadline_s=float(
                os.environ.get("BENCH_DEADLINE_S", "2.0")))
    cfg = Config(llm_provider="tpu", model_name=MODEL,
                 decode_slots=NUM_SESSIONS, max_model_len=2048,
                 default_context_window=2048, prefill_chunk=512,
                 dtype="bfloat16", port=PORT, monitoring_port=PORT + 1,
                 **extra,
                 # Plain chat serving path (no tool-section system
                 # prompt): keeps the measured prompt identical to the
                 # reference's bench conditions; the agent path has its
                 # own tests.
                 enable_agent=False,
                 # int8 weights are the serving default for the bench:
                 # measurably faster per decode step than bf16 now that
                 # the dequant-fused kernels stream int8 bytes
                 # (ops/pallas_int8.py), and the same config the
                 # README's model table quotes.
                 quantize=os.environ.get("BENCH_QUANTIZE", "int8"))
    if MODE == "multiturn":
        mt_sessions = int(os.environ.get("BENCH_MT_SESSIONS",
                                         str(NUM_SESSIONS)))
        # Slot pressure is the whole scenario: fewer slots than
        # sessions, so a follow-up turn always returns to an evicted
        # session.
        slots = int(os.environ.get("BENCH_MT_SLOTS",
                                   str(max(1, mt_sessions // 2))))
        if os.environ.get("BENCH_MT_PHASE"):
            # Child process: one phase with the budget the parent set.
            budget = float(os.environ.get("BENCH_KV_BUDGET_MB", "0"))
            cfg = Config(llm_provider="tpu", model_name=MODEL,
                         decode_slots=slots, max_model_len=2048,
                         default_context_window=2048,
                         prefill_chunk=512, dtype="bfloat16",
                         port=PORT, monitoring_port=PORT + 1,
                         enable_agent=False,
                         kv_host_budget_mb=budget,
                         quantize=os.environ.get("BENCH_QUANTIZE",
                                                 "int8"))
            turns = int(os.environ.get("BENCH_MT_TURNS", "3"))
            max_tokens = int(os.environ.get("BENCH_MT_MAX_TOKENS",
                                            "32"))
            phase = asyncio.run(
                _mt_phase(cfg, mt_sessions, turns, max_tokens))
            print(json.dumps(phase), flush=True)
            return

        r = bench_multiturn()
        on_p50 = (r["on"]["followup_ttft_ms"] or {}).get("p50")
        print(json.dumps({
            "metric": (f"multiturn follow-up-turn TTFT p50 ms, {MODEL}: "
                       f"{r['sessions']} sessions x {r['turns']} turns "
                       f"on {slots} slots, host pool "
                       f"{r['kv_budget_mb']:.0f} MB (off p50 "
                       f"{r['off']['followup_ttft_ms']['p50']} ms, "
                       f"restore hit ratio "
                       f"{r['on']['restore_hit_ratio']}, p50 speedup "
                       f"{r['followup_ttft_p50_speedup']}x)"),
            "value": on_p50,
            "unit": "ms",
            # For this mode the baseline is the engine's own
            # re-prefill path: >1 means the restore tier is winning.
            "vs_baseline": r["followup_ttft_p50_speedup"],
            "multiturn": r,
        }), flush=True)
        return
    if MODE == "longctx":
        ctx = int(os.environ.get("BENCH_LC_CTX", "384"))
        sessions = int(os.environ.get("BENCH_LC_SESSIONS", "8"))
        slots = int(os.environ.get("BENCH_LC_SLOTS", "2"))
        max_tokens = int(os.environ.get("BENCH_LC_MAX_TOKENS", "16"))
        if os.environ.get("BENCH_LC_PHASE"):
            # Child process: one phase with the kv_quant the parent
            # set. Weight quantization stays OFF in both phases — it
            # is orthogonal to the KV tier and would only blur the
            # comparison; speculative decoding is off because the
            # int8 phase rejects it (compat matrix) and the control
            # must match.
            from fasttalk_tpu.models.configs import get_model_config

            m = get_model_config(MODEL)
            bucket = 1 << (ctx + 96 - 1).bit_length()
            bf16_entry_mb = 2 * m.num_layers * bucket \
                * m.num_kv_heads * m.head_dim * 2 / 2**20
            budget = float(os.environ.get(
                "BENCH_LC_BUDGET_MB",
                str(round(3.5 * bf16_entry_mb, 3))))
            cfg = Config(llm_provider="tpu", model_name=MODEL,
                         decode_slots=slots, max_model_len=2048,
                         default_context_window=2048,
                         prefill_chunk=512, dtype="bfloat16",
                         port=PORT, monitoring_port=PORT + 1,
                         enable_agent=False, spec_decode="off",
                         quantize="none",
                         kv_host_budget_mb=budget,
                         kv_park_idle_s=0.0,
                         kv_quant=os.environ["BENCH_LC_PHASE"])
            phase = asyncio.run(
                _lc_phase(cfg, sessions, ctx, max_tokens))
            print(json.dumps(phase), flush=True)
            return
        r = bench_longctx()
        print(json.dumps({
            "metric": (f"longctx parked-session capacity ratio "
                       f"(int8 KV vs bf16), {MODEL}: {r['sessions']} "
                       f"sessions x ~{r['ctx_tokens']} ctx tokens on "
                       f"a fixed {r['budget_mb']:.1f} MB host budget "
                       f"(bf16 {r['bf16']['parked_sessions']} x "
                       f"{r['bf16']['per_session_mb']} MB vs int8 "
                       f"{r['int8']['parked_sessions']} x "
                       f"{r['int8']['per_session_mb']} MB; restore "
                       f"p50 {r['bf16']['restore_p50_ms']} -> "
                       f"{r['int8']['restore_p50_ms']} ms, "
                       f"{r['restore_p50_speedup']}x; decode tok/s "
                       f"ratio {r['decode_tok_s_ratio']})"),
            "value": r["parked_capacity_ratio"],
            "unit": "x",
            # For this mode the baseline is the bf16 KV cache on the
            # same budget: >= 1.8 means the quantized tier is holding
            # ~double the sessions per byte.
            "vs_baseline": r["parked_capacity_ratio"],
            "longctx": r,
        }), flush=True)
        return
    if MODE == "int4":
        max_tokens = int(os.environ.get("BENCH_I4_MAX_TOKENS", "64"))
        slots = int(os.environ.get("BENCH_I4_SLOTS", "4"))
        if os.environ.get("BENCH_I4_PHASE"):
            # Child process: one weight tier. KV knobs at defaults and
            # spec decode off in every phase — only the weight tier
            # may differ between the children.
            cfg = Config(llm_provider="tpu", model_name=MODEL,
                         decode_slots=slots, max_model_len=512,
                         default_context_window=512,
                         prefill_chunk=512, dtype="bfloat16",
                         port=PORT, monitoring_port=PORT + 1,
                         enable_agent=False, spec_decode="off",
                         weight_quant=os.environ["BENCH_I4_PHASE"])
            phase = asyncio.run(_i4_phase(cfg, max_tokens))
            print(json.dumps(phase), flush=True)
            return
        r = bench_int4()
        print(json.dumps({
            "metric": (f"int4 resident sessions x context envelope "
                       f"ratio (int4+scales weights vs bf16), {MODEL}: "
                       f"fixed {r['budget_mb']:.1f} MB HBM budget, "
                       f"weight bytes "
                       f"{r['weight_bytes']['off'] / 2**20:.1f} -> "
                       f"{r['weight_bytes']['int4'] / 2**20:.1f} MB "
                       f"(group {r['group']}), KV envelope "
                       f"{r['envelope_token_rows']['off']} -> "
                       f"{r['envelope_token_rows']['int4']} token-rows"
                       f"; decode tok/s off/int8/int4 "
                       f"{r['off']['decode_tok_s']}/"
                       f"{r['int8']['decode_tok_s']}/"
                       f"{r['int4']['decode_tok_s']} (int4 vs int8 "
                       f"{r['decode_tok_s_int4_vs_int8']})"),
            "value": r["envelope_ratio_int4_vs_bf16"],
            "unit": "x",
            # For this mode the baseline is bf16 weights on the SAME
            # budget: >= 2 means the 4-bit tier at least doubles what
            # the budget can hold resident.
            "vs_baseline": r["envelope_ratio_int4_vs_bf16"],
            "int4": r,
        }), flush=True)
        return
    if MODE == "roofline":
        slots = int(os.environ.get("BENCH_RF_SLOTS", "8"))
        max_tokens = int(os.environ.get("BENCH_RF_MAX_TOKENS", "24"))
        if os.environ.get("BENCH_RF_PHASE"):
            # Child process: one sweep cell. Weight quant off by
            # default so the KV-tier and kernel axes are the only
            # variables (the TPU driver can re-pin BENCH_QUANTIZE);
            # spec off because the int8 cells reject it and every cell
            # must measure the same decode family.
            kv_quant = os.environ.get("BENCH_RF_KV", "none")
            layout = os.environ.get("BENCH_RF_LAYOUT", "dense")
            kernel = os.environ.get("BENCH_RF_KERNEL", "xla")
            cfg = Config(llm_provider="tpu", model_name=MODEL,
                         decode_slots=slots, max_model_len=1024,
                         default_context_window=1024,
                         prefill_chunk=512, dtype="bfloat16",
                         port=PORT, monitoring_port=PORT + 1,
                         enable_agent=False, spec_decode="off",
                         quantize=os.environ.get("BENCH_QUANTIZE",
                                                 "none"),
                         kv_quant=kv_quant, kv_layout=layout,
                         kv_block_size=int(os.environ.get(
                             "KV_BLOCK_SIZE", "16")),
                         kv_host_budget_mb=0.0,
                         use_pallas_attention=(kernel == "pallas"))
            phase = asyncio.run(_rf_phase(cfg, max_tokens))
            print(json.dumps(phase), flush=True)
            return
        r = bench_roofline()
        b = r["best"]
        frac = r["best_frac_of_ceiling"]
        print(json.dumps({
            "metric": (f"roofline sweep best decode tok/s, {MODEL}: "
                       f"{len(r['rows'])} cells (kv x layout x kernel "
                       f"x steps_per_call) at {slots} slots; best = "
                       f"kv={b['kv_quant']} {b['kv_layout']} "
                       f"{b['kernel']} steps={b['steps_per_call']}"
                       + (f", {frac:.0%} of first-order HBM ceiling"
                          if frac is not None else
                          " (no HBM peak for this device kind)")),
            "value": b["tok_s"],
            "unit": "tok/s",
            "vs_baseline": round(b["tok_s"] / BASELINE_TOKS, 2),
            "roofline": r,
        }), flush=True)
        return
    if MODE == "radix":
        agents = int(os.environ.get("BENCH_RX_AGENTS", "4"))
        turns = int(os.environ.get("BENCH_RX_TURNS", "4"))
        max_tokens = int(os.environ.get("BENCH_RX_MAX_TOKENS", "32"))
        if os.environ.get("BENCH_RX_PHASE"):
            # Child process: one phase. Paged layout in BOTH phases
            # (the tree requires it, and the off control must differ
            # by exactly one knob); host pool off so park/restore
            # can't serve the prefix either way.
            on = os.environ["BENCH_RX_PHASE"] == "on"
            cfg = Config(llm_provider="tpu", model_name=MODEL,
                         decode_slots=agents, max_model_len=2048,
                         default_context_window=2048,
                         prefill_chunk=512, dtype="bfloat16",
                         port=PORT, monitoring_port=PORT + 1,
                         enable_agent=False, spec_decode="off",
                         kv_host_budget_mb=0.0, kv_layout="paged",
                         kv_radix_enabled=on,
                         quantize=os.environ.get("BENCH_QUANTIZE",
                                                 "int8"))
            out = asyncio.run(_rx_phase(cfg, agents, turns,
                                        max_tokens))
            print(json.dumps(out), flush=True)
            return
        r = bench_radix()
        on_p50 = (r["on"]["followup_ttft_ms"] or {}).get("p50")
        print(json.dumps({
            "metric": (f"radix follow-up-turn TTFT p50 ms, {MODEL}: "
                       f"{r['agents']} agents x {r['turns']} turns, "
                       f"fresh session per turn (off p50 "
                       f"{r['off']['followup_ttft_ms']['p50']} ms, "
                       f"hit rate {r['hit_rate']}, bytes saved "
                       f"{r['bytes_saved']}, p50 speedup "
                       f"{r['followup_ttft_p50_speedup']}x)"),
            "value": on_p50,
            "unit": "ms",
            # Baseline is the engine's own full-re-prefill path:
            # >1 means the tree is winning; acceptance wants >= 2.
            "vs_baseline": r["followup_ttft_p50_speedup"],
            "radix": r,
        }), flush=True)
        return
    if MODE == "paged":
        sessions = int(os.environ.get("BENCH_PG_SESSIONS", "8"))
        max_len = int(os.environ.get("BENCH_PG_MAX_LEN", "2048"))
        rows = int(os.environ.get("BENCH_PG_KV_ROWS", "6144"))
        bs = int(os.environ.get("KV_BLOCK_SIZE", "16"))
        max_tokens = int(os.environ.get("BENCH_PG_MAX_TOKENS", "16"))
        if os.environ.get("BENCH_PG_PHASE"):
            # Child process: one (phase, layout) pair. Weight quant
            # and spec decode off in every phase — orthogonal knobs
            # would only blur the layout comparison; the host pool is
            # off so admission capacity is purely the device layout's.
            phase = os.environ["BENCH_PG_PHASE"]
            layout = os.environ["BENCH_PG_LAYOUT"]
            common = dict(llm_provider="tpu", model_name=MODEL,
                          prefill_chunk=512, dtype="bfloat16",
                          port=PORT, monitoring_port=PORT + 1,
                          enable_agent=False, spec_decode="off",
                          quantize="none", kv_host_budget_mb=0.0,
                          kv_layout=layout, kv_block_size=bs)
            if phase == "admission":
                slots = (sessions if layout == "paged"
                         else max(1, rows // max_len))
                cfg = Config(decode_slots=slots, max_model_len=max_len,
                             default_context_window=max_len,
                             kv_pool_blocks=(rows // bs
                                             if layout == "paged"
                                             else 0),
                             **common)
                out = asyncio.run(_pg_admission_phase(
                    cfg, sessions, _pg_mixed_contexts(sessions,
                                                      max_len),
                    max_tokens))
            else:
                # Throughput pair: identical slot count, paged pool
                # at the dense-equivalent size — the overhead control.
                tslots = int(os.environ.get("BENCH_PG_TPUT_SLOTS",
                                            "4"))
                cfg = Config(decode_slots=tslots, max_model_len=512,
                             default_context_window=512, **common)
                out = asyncio.run(_pg_tput_phase(cfg, 64))
            print(json.dumps(out), flush=True)
            return
        r = bench_paged()
        print(json.dumps({
            "metric": (f"paged-KV peak concurrent sessions on a fixed "
                       f"{r['kv_rows_budget']}-row KV budget, {MODEL}: "
                       f"mixed contexts {r['contexts']}, dense "
                       f"{r['admission']['dense']['peak_concurrent']} "
                       f"(hard cap {r['dense_slots']} slots) vs paged "
                       f"{r['admission']['paged']['peak_concurrent']} "
                       f"({r['concurrent_ratio']}x); short-context "
                       f"decode tok/s ratio "
                       f"{r['throughput']['ratio']}; aliased-prefix "
                       f"savings {r['alias_saved_rows']} rows"),
            "value": r["admission"]["paged"]["peak_concurrent"],
            "unit": "sessions",
            # For this mode the baseline is the dense layout on the
            # SAME budget: > 1 means block-granular admission is
            # holding more of the mixed fleet resident.
            "vs_baseline": r["concurrent_ratio"],
            "paged": r,
        }), flush=True)
        return
    if MODE == "disagg":
        # The role-split-vs-mixed pair standalone (the same phases the
        # fleet headline tail carries), with the decode ITL p99 gain
        # as the gated value.
        d = bench_fleet_disagg()
        print(json.dumps({
            "metric": (f"disagg decode ITL p99 gain, {MODEL}: "
                       f"role-split (prefill|decode tiers) vs mixed "
                       f"on 2 replicas (split p99 "
                       f"{d['split']['decode_itl_ms']['p99']} ms vs "
                       f"mixed {d['mixed']['decode_itl_ms']['p99']} "
                       f"ms; {d['split']['handoffs']} handoffs, "
                       f"{d['split']['fallbacks']} fallbacks; long "
                       f"TTFT p50 {d['split']['long_ttft_ms']['p50']} "
                       f"vs {d['mixed']['long_ttft_ms']['p50']} ms; "
                       f"{d['error_frames']} error frames)"),
            "value": d["decode_itl_p99_gain"],
            "unit": "x",
            # >1 means the split protected the decode tail.
            "vs_baseline": d["decode_itl_p99_gain"],
            "disagg": d,
        }), flush=True)
        return
    if MODE == "fleet":
        replicas = int(os.environ.get("BENCH_FLEET_REPLICAS", "2"))
        sessions = int(os.environ.get("BENCH_FLEET_SESSIONS", "8"))
        slots = int(os.environ.get("BENCH_FLEET_SLOTS",
                                   str(max(1, sessions // replicas))))
        max_tokens = int(os.environ.get("BENCH_FLEET_MAX_TOKENS", "32"))
        if os.environ.get("BENCH_FLEET_MIGRATE"):
            # Child: one side of the migration-vs-reprefill pair.
            on = os.environ["BENCH_FLEET_MIGRATE"] == "on"
            phase = asyncio.run(_fleet_migration_phase(
                _fleet_fabric_cfg(slots), on,
                int(os.environ.get("BENCH_FLEET_MIG_SESSIONS", "4"))))
            print(json.dumps(phase), flush=True)
            sys.stdout.flush()
            os._exit(0)
        if os.environ.get("BENCH_FLEET_DISAGG"):
            # Child: one side of the role-split-vs-mixed pair.
            split = os.environ["BENCH_FLEET_DISAGG"] == "split"
            phase = asyncio.run(_fleet_disagg_phase(
                _fleet_fabric_cfg(slots), split,
                int(os.environ.get("BENCH_FLEET_DISAGG_SESSIONS",
                                   "2"))))
            print(json.dumps(phase), flush=True)
            sys.stdout.flush()
            os._exit(0)
        if os.environ.get("BENCH_FLEET_ROLLING"):
            # Child: the rolling-restart drill.
            n = int(os.environ["BENCH_FLEET_ROLLING"])
            phase = asyncio.run(_fleet_rolling_phase(
                _fleet_fabric_cfg(slots), n, sessions))
            print(json.dumps(phase), flush=True)
            sys.stdout.flush()
            os._exit(0)
        if os.environ.get("BENCH_FLEET_PHASE"):
            # Child process: one fleet size, then hard-exit (no XLA
            # multi-engine teardown).
            n = int(os.environ["BENCH_FLEET_PHASE"])
            cfg = Config(llm_provider="tpu", model_name=MODEL,
                         decode_slots=slots, max_model_len=2048,
                         default_context_window=2048,
                         prefill_chunk=512, dtype="bfloat16",
                         port=PORT, monitoring_port=PORT + 1,
                         enable_agent=False,
                         quantize=os.environ.get("BENCH_QUANTIZE",
                                                 "int8"))
            phase = asyncio.run(_fleet_phase(cfg, n, sessions,
                                             max_tokens))
            print(json.dumps(phase), flush=True)
            sys.stdout.flush()
            os._exit(0)
        r = bench_fleet(replicas, sessions, slots)
        fabric = bench_fleet_fabric(replicas, sessions)
        r["fabric"] = fabric
        disagg = bench_fleet_disagg()
        r["disagg"] = disagg
        fo = (r["fleet"].get("failover") or {})
        roll = fabric["rolling_restart"]
        log(f"fabric headline: migration follow-up TTFT "
            f"{fabric['followup_ttft_speedup']}x vs re-prefill; "
            f"rolling restart {roll['error_frames']} error frames / "
            f"{roll['resumed_events']} resumed")
        log(f"disagg headline: decode ITL p99 gain "
            f"{disagg['decode_itl_p99_gain']}x (role-split vs mixed), "
            f"{disagg['split']['handoffs']} handoffs, "
            f"{disagg['error_frames']} error frames")
        print(json.dumps({
            "metric": (f"fleet aggregate WS tok/s, {MODEL}: "
                       f"{r['sessions']} sessions on "
                       f"{r['fleet']['replicas']} replicas x "
                       f"{r['slots_per_replica']} slots, "
                       f"{r['cores']} core(s) (single-replica"
                       f" {r['single']['agg_tps']} tok/s, speedup "
                       f"{r['agg_tps_speedup']}x, p50 TTFT speedup "
                       f"{r['p50_ttft_speedup']}x; failover resumed "
                       f"{fo.get('resumed')}/{fo.get('affected')} "
                       f"streams, {fo.get('errors')} errors, resume "
                       f"p50 "
                       f"{(fo.get('resume_latency_ms') or {}).get('p50')}"
                       f" ms; drain-migrate follow-up TTFT "
                       f"{fabric['followup_ttft_speedup']}x vs "
                       f"re-prefill, rolling restart "
                       f"{roll['error_frames']} error frames / "
                       f"{roll['resumed_events']} resumed; disagg "
                       f"decode ITL p99 gain "
                       f"{disagg['decode_itl_p99_gain']}x role-split "
                       f"vs mixed, {disagg['split']['handoffs']} "
                       f"handoffs, {disagg['error_frames']} error "
                       f"frames)"),
            "value": r["fleet"]["agg_tps"],
            "unit": "tok/s",
            # For this mode the baseline is the single-replica run:
            # >1 means scaling out is buying capacity.
            "vs_baseline": r["agg_tps_speedup"],
            "fleet": r,
            "disagg": disagg,
        }), flush=True)
        return
    if MODE == "overload":
        r = asyncio.run(bench_overload(cfg))
        r["perf"] = perf_attribution()
        print(json.dumps({
            "metric": (f"overload goodput tok/s, {MODEL}: open-loop "
                       f"{r['arrival_rate_rps']:.0f} req/s x "
                       f"{r['duration_s']:.0f}s, bound "
                       f"{r['queue_bound']} (max depth "
                       f"{r['max_queue_depth']}), shed "
                       f"{r['shed_rate']:.1%}, expired "
                       f"{r['expiry_rate']:.1%}, admitted queue-wait "
                       f"p50/p95/p99 {r['queue_wait_ms']['p50']:.0f}/"
                       f"{r['queue_wait_ms']['p95']:.0f}/"
                       f"{r['queue_wait_ms']['p99']:.0f} ms, SLO "
                       f"goodput {fmt_goodput(r['slo_goodput'])}"),
            "value": r["goodput_tok_s"],
            "unit": "tok/s",
            "vs_baseline": round(r["goodput_tok_s"] / BASELINE_TOKS, 2),
            "overload": r,
        }), flush=True)
        return
    if MODE == "structured":
        from fasttalk_tpu.engine.factory import build_engine

        t0 = time.monotonic()
        engine = build_engine(cfg)
        engine.start()
        log(f"engine up in {time.monotonic() - t0:.1f}s")
        try:
            r = asyncio.run(bench_structured(engine))
        finally:
            engine.shutdown()
        jf = r["jump_forward"]
        print(json.dumps({
            "metric": (f"structured mask overhead frac, {MODEL}: "
                       f"constrained {r['constrained_tok_s']} vs "
                       f"unconstrained {r['unconstrained_tok_s']} "
                       f"tok/s greedy (target < 0.05); jump-forward "
                       f"forced {jf['forced_fraction']:.0%} of "
                       f"{jf['doc_tokens']} tokens, e2e "
                       f"{jf['e2e_off_ms']:.0f} -> "
                       f"{jf['e2e_on_ms']:.0f} ms "
                       f"({jf['e2e_speedup']}x)"),
            "value": r["mask_overhead_frac"],
            "unit": "frac",
            # For this mode the baseline is the unconstrained tok/s on
            # the same engine: the ratio shows what the mask costs.
            "vs_baseline": round(r["constrained_tok_s"]
                                 / r["unconstrained_tok_s"], 3),
            "structured": r,
        }), flush=True)
        return
    if MODE == "chaos":
        phase = os.environ.get("BENCH_CHAOS_PHASE", "")
        if phase in ("control", "mttr"):
            # Child process: one phase, then hard-exit (a worked
            # engine's in-process XLA-CPU teardown — let alone a
            # crash->restart cycle's abandoned dispatches — is the
            # documented heap-corruption trap the multiturn/fleet
            # benches also isolate away).
            from fasttalk_tpu.engine.factory import build_engine

            engine = build_engine(cfg)
            engine.start()
            if phase == "control":
                d = asyncio.run(bench_chaos(engine))["control"]
            else:
                d = asyncio.run(_chaos_mttr_drill(engine))
            print(json.dumps(d), flush=True)
            sys.stdout.flush()
            os._exit(0)
        r = bench_chaos_main()
        fo = r["failover"]
        ctl = r["control"]
        print(json.dumps({
            "metric": (f"chaos engine-restart MTTR-to-first-token p50 "
                       f"ms, {MODEL} (restart p50 "
                       f"{r['restart_p50_ms']} ms over 3 injected "
                       f"crash_thread drills); failpoints-off control "
                       f"delta {ctl['delta_frac']:+.2%} "
                       f"(off {ctl['off_tps']} vs armed-inert "
                       f"{ctl['armed_tps']} tok/s, target < 1%); "
                       f"router failover resumed {fo['resumed']}/"
                       f"{fo['affected']} streams, {fo['errors']} "
                       f"errors, resume p50 {fo['resume_p50_ms']} ms"),
            "value": r["mttr_p50_ms"],
            "unit": "ms",
            # For this mode the baseline is the failpoints-off phase:
            # ~1.0 IS the result (armed-inert costs nothing).
            "vs_baseline": round(ctl["armed_tps"] / ctl["off_tps"], 3),
            "chaos": r,
        }), flush=True)
        return
    if MODE == "profiler":
        from fasttalk_tpu.engine.factory import build_engine

        t0 = time.monotonic()
        engine = build_engine(cfg)
        engine.start()
        log(f"engine up in {time.monotonic() - t0:.1f}s")
        try:
            r = asyncio.run(bench_profiler(engine))
        finally:
            engine.shutdown()
        ctl = r["control"]
        print(json.dumps({
            "metric": (f"continuous-profiler overhead delta frac, "
                       f"{MODEL}: sampler off {ctl['off_tps']} vs on "
                       f"{ctl['on_tps']} tok/s at "
                       f"{r['sampler']['hz']:g} Hz "
                       f"({r['sampler']['samples']} samples; target "
                       f"|delta| < 0.01)"),
            "value": ctl["delta_frac"],
            "unit": "frac",
            # For this mode the baseline is the sampler-off phase:
            # ~1.0 IS the result (sampling-on costs nothing).
            "vs_baseline": round(ctl["on_tps"] / ctl["off_tps"], 3),
            "profiler": r,
        }), flush=True)
        return
    if MODE == "ws":
        r = asyncio.run(bench_ws(cfg))
        seam = "WebSocket"
    else:
        from fasttalk_tpu.engine.factory import build_engine

        t0 = time.monotonic()
        engine = build_engine(cfg)
        engine.start()
        log(f"engine up in {time.monotonic() - t0:.1f}s")
        try:
            r = asyncio.run(bench_engine(engine))
        finally:
            engine.shutdown()
        seam = "engine-seam"

    # SLO goodput over the measured passes (warmup requests cleared
    # after compiles landed): the fraction of requests that met every
    # latency objective, next to the raw throughput headline.
    slo_goodput, _ = slo_goodput_summary()
    slo_note = "" if slo_goodput is None \
        else f"; SLO goodput {fmt_goodput(slo_goodput)}"
    perf = perf_attribution()
    if perf is not None:
        log(f"  perf attribution: busy {perf['device_busy_frac']:.0%} "
            f"/ host gap {perf['host_gap_frac']:.0%} / idle "
            f"{perf['idle_frac']:.0%}; occupancy "
            f"{perf['occupancy_mean']}; padding waste "
            f"{perf['padding_waste_frac']}; MFU {perf['mfu']}")
    print(json.dumps({
        "metric": (f"{seam} output tok/s, {MODEL}, "
                   f"{NUM_SESSIONS} concurrent sessions (p50 TTFT "
                   f"{r['p50_ttft_ms']:.0f}ms; 1-session "
                   f"{r['single_tps']:.1f} tok/s{slo_note})"),
        "value": round(r["agg_tps"], 1),
        "unit": "tok/s",
        "vs_baseline": round(r["agg_tps"] / BASELINE_TOKS, 2),
        **({} if slo_goodput is None
           else {"slo_goodput": slo_goodput}),
        **({} if perf is None else {"perf": perf}),
    }), flush=True)


if __name__ == "__main__":
    main()
