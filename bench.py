"""Benchmark: streamed output tokens/sec on the in-tree TPU engine.

Measures the BASELINE north-star metric — output tok/s and p50 TTFT for
Llama-3.2-1B with 16 concurrent streaming sessions — at the engine's
async-generator seam (the same seam the WebSocket server consumes, so
per-token asyncio delivery overhead is included; only the socket write
itself is excluded).

Weights are random-init (no checkpoint in the image): compute cost is
identical to real weights, which is what throughput measures.

Prints exactly ONE JSON line on stdout:
  {"metric": ..., "value": N, "unit": "tok/s", "vs_baseline": N}
vs_baseline compares against the reference's published ~150 tok/s for
llama3.2:1b on an RTX 3090 (reference: README.md:474, BASELINE.md).
"""

from __future__ import annotations

import asyncio
import json
import statistics
import sys
import time


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


import os

BASELINE_TOKS = 150.0  # reference llama3.2:1b on RTX 3090 (README.md:474)
# Env overrides are for smoke-testing on CPU; the driver runs defaults.
MODEL = os.environ.get("BENCH_MODEL", "llama3.2:1b")
NUM_SESSIONS = int(os.environ.get("BENCH_SESSIONS", "16"))
MAX_TOKENS = int(os.environ.get("BENCH_MAX_TOKENS", "128"))
PROMPT = ("You are a concise assistant for a realtime voice app. "
          "Explain, in plain language, how a systolic array multiplies "
          "matrices and why that favours large batched matmuls.")


async def run_session(engine, i: int, max_tokens: int) -> dict:
    from fasttalk_tpu.engine.engine import GenerationParams

    t0 = time.monotonic()
    ttft = None
    tokens = 0
    params = GenerationParams(temperature=0.7, top_k=40, top_p=0.9,
                              max_tokens=max_tokens)
    messages = [{"role": "user", "content": f"[session {i}] {PROMPT}"}]
    async for event in engine.generate(f"bench-req-{i}", f"bench-sess-{i}",
                                       messages, params):
        if event["type"] == "token":
            if ttft is None:
                ttft = (time.monotonic() - t0) * 1000.0
        elif event["type"] == "done":
            tokens = event["stats"]["tokens_generated"]
        elif event["type"] == "error":
            raise RuntimeError(f"generation failed: {event}")
    return {"tokens": tokens, "ttft_ms": ttft or 0.0,
            "wall_s": time.monotonic() - t0}


async def bench(engine) -> dict:
    # Warmup: trigger prefill + decode compiles for every shape the
    # measurement hits — the single-session path AND the concurrent-burst
    # path (batched prefill compiles a full-batch group shape).
    log("warmup (compiling prefill + decode buckets)...")
    t0 = time.monotonic()
    await run_session(engine, 999, max_tokens=8)
    engine.release_session("bench-sess-999")
    await asyncio.gather(
        *(run_session(engine, 900 + i, max_tokens=8)
          for i in range(NUM_SESSIONS)))
    for i in range(NUM_SESSIONS):
        engine.release_session(f"bench-sess-{900 + i}")
    log(f"warmup done in {time.monotonic() - t0:.1f}s")

    log("single-session run...")
    single = await run_session(engine, 0, MAX_TOKENS)
    engine.release_session("bench-sess-0")
    single_tps = single["tokens"] / single["wall_s"]
    log(f"  1 session: {single['tokens']} tok in {single['wall_s']:.2f}s "
        f"= {single_tps:.1f} tok/s, TTFT {single['ttft_ms']:.0f}ms")

    log(f"{NUM_SESSIONS} concurrent sessions...")
    t0 = time.monotonic()
    results = await asyncio.gather(
        *(run_session(engine, i, MAX_TOKENS) for i in range(NUM_SESSIONS)))
    wall = time.monotonic() - t0
    for i in range(NUM_SESSIONS):
        engine.release_session(f"bench-sess-{i}")
    total_tokens = sum(r["tokens"] for r in results)
    agg_tps = total_tokens / wall
    p50_ttft = statistics.median(r["ttft_ms"] for r in results)
    log(f"  {NUM_SESSIONS} sessions: {total_tokens} tok in {wall:.2f}s "
        f"= {agg_tps:.1f} tok/s aggregate, p50 TTFT {p50_ttft:.0f}ms")

    return {"single_tps": single_tps, "single_ttft_ms": single["ttft_ms"],
            "agg_tps": agg_tps, "p50_ttft_ms": p50_ttft}


def main() -> None:
    import jax

    log(f"jax devices: {jax.devices()}")

    from fasttalk_tpu.engine.factory import build_engine
    from fasttalk_tpu.utils.config import Config

    cfg = Config(llm_provider="tpu", model_name=MODEL,
                 decode_slots=NUM_SESSIONS, max_model_len=2048,
                 default_context_window=2048, prefill_chunk=512,
                 dtype="bfloat16",
                 # int8 weights are the serving default for the bench:
                 # measurably faster per decode step than bf16 now that
                 # the dequant-fused kernels stream int8 bytes
                 # (ops/pallas_int8.py), and the same config the
                 # README's model table quotes.
                 quantize=os.environ.get("BENCH_QUANTIZE", "int8"))
    t0 = time.monotonic()
    engine = build_engine(cfg)
    engine.start()
    log(f"engine up in {time.monotonic() - t0:.1f}s")
    try:
        r = asyncio.run(bench(engine))
    finally:
        engine.shutdown()

    print(json.dumps({
        "metric": (f"WebSocket output tok/s, {MODEL}, "
                   f"{NUM_SESSIONS} concurrent sessions (p50 TTFT "
                   f"{r['p50_ttft_ms']:.0f}ms; 1-session "
                   f"{r['single_tps']:.1f} tok/s)"),
        "value": round(r["agg_tps"], 1),
        "unit": "tok/s",
        "vs_baseline": round(r["agg_tps"] / BASELINE_TOKS, 2),
    }), flush=True)


if __name__ == "__main__":
    main()
