#!/bin/bash
# Container entrypoint (reference: entrypoint.sh — permissions + config
# echo + exec the service). Single process: engine is in-tree.
set -e

mkdir -p "${LOG_PATH:-/app/logs}" "${MODEL_PATH:-/app/models}" 2>/dev/null || true

echo "=== FastTalk-TPU ==="
echo "provider:   ${LLM_PROVIDER:-tpu}"
echo "model:      ${LLM_MODEL:-llama3.2:1b}"
echo "device:     ${COMPUTE_DEVICE:-tpu}"
echo "port:       ${LLM_PORT:-8000} (monitoring: ${LLM_MONITORING_PORT:-9092})"
echo "tp x dp:    ${TPU_TP_SIZE:-1} x ${TPU_DP_SIZE:-1}"
echo "slots/ctx:  ${TPU_DECODE_SLOTS:-16} slots, ${TPU_MAX_MODEL_LEN:-8192} tokens"
echo "===================="

exec python main.py websocket "$@"
