"""Prepared-weight cache: Orbax store of the engine-ready param pytree.

The reference's only "checkpoint" story was the external engines caching
raw HF downloads in docker volumes (reference: docker-compose.vllm.yml:
58-59 vllm_cache volume; SURVEY.md §5 checkpoint/resume: none in-tree).
Here the expensive part of startup is not the download but the
transform: safetensors -> transpose -> stack layers -> cast -> (int8
quantize) -> (TP shard). This module caches the FINAL pytree — already
stacked, cast, quantized and shard-layout-aware — so a restart restores
straight into device shards at Orbax/TensorStore speed and skips the
whole transform pipeline.

Cache key: model name + dtype + quantize + mesh shape (meta.json). Any
mismatch ignores the cache (it is re-written after the slow load).
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import jax.numpy as jnp

from fasttalk_tpu.models.configs import ModelConfig
from fasttalk_tpu.models.llama import init_params
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("models.prepared_cache")

_META = "fasttalk_meta.json"


def checkpoint_fingerprint(ckpt_dir: str | None) -> list | None:
    """Identity of the source checkpoint files (name, size, mtime): a
    re-downloaded/updated checkpoint must invalidate the prepared cache,
    or a restart would silently keep serving the stale weights."""
    if not ckpt_dir or not os.path.isdir(ckpt_dir):
        return None
    out = []
    for f in sorted(os.listdir(ckpt_dir)):
        if f.endswith((".safetensors", ".json")):
            st = os.stat(os.path.join(ckpt_dir, f))
            out.append([f, st.st_size, int(st.st_mtime)])
    return out


def _tier(quantize: bool | str) -> str:
    """Normalize the quantize argument: legacy bool (int8 on/off) or a
    WEIGHT_QUANT tier string. Returns "none" | "int8" | "int4"."""
    t = quantize if isinstance(quantize, str) else (
        "int8" if quantize else "none")
    return {"off": "none", "": "none"}.get(t, t)


def cache_meta(cfg: ModelConfig, dtype, quantize: bool | str, mesh,
               ckpt_dir: str | None = None, group: int = 128) -> dict:
    tier = _tier(quantize)
    meta = {
        # 2: int8 now also row-quantizes the embedding (ops/quant.py
        # EMBED_LEAF) — format bump invalidates r2-era caches whose
        # pytree lacks the embed {q, s} dict.
        "format": 2,
        "model": cfg.name,
        "dtype": jnp.dtype(dtype).name,
        "quantize": tier,
        "mesh": dict(mesh.shape) if mesh is not None else None,
        # Device topology: Orbax sharding metadata references concrete
        # device names, and restoring under a different topology (e.g.
        # a store written on 1 CPU device read under a forced 8-device
        # CPU mesh) spews ERROR-level device-not-found records from
        # orbax internals even when the fallback succeeds. A topology
        # mismatch skips the cache and re-transforms instead.
        "devices": [jax.devices()[0].platform, jax.device_count()],
        "source": checkpoint_fingerprint(ckpt_dir),
    }
    if tier == "int4":
        # Group size changes the scale-leaf shapes; only present for
        # int4 so pre-existing none/int8 metas keep comparing equal.
        meta["group"] = int(group)
    return meta


def cache_dir(model_path: str, meta: dict) -> str:
    mesh = meta["mesh"] or {}
    quant = meta["quantize"]
    if meta.get("group"):
        quant = f"{quant}-g{meta['group']}"
    tag = "-".join([meta["model"].replace(":", "_"), meta["dtype"],
                    quant,
                    "x".join(f"{k}{v}" for k, v in sorted(mesh.items()))
                    or "single"])
    return os.path.join(model_path, ".prepared", tag)


def abstract_params(cfg: ModelConfig, dtype, quantize: bool | str, mesh,
                    group: int = 128) -> Any:
    """ShapeDtypeStruct pytree (with shardings when meshed) matching what
    the factory's load path produces — the restore target."""
    from fasttalk_tpu.ops.quant import QUANTIZED_LEAVES
    from fasttalk_tpu.quantization.int4 import INT4_LEAVES

    tier = _tier(quantize)
    quantize = tier != "none"
    shapes = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(0), dtype))

    def to_abstract(path, sds):
        name = str(getattr(path[-1], "key", path[-1]))
        parent = ""
        keys = [str(getattr(k, "key", k)) for k in path]
        if len(keys) >= 2:
            parent = keys[-2]

        def with_sharding(shape, dt, leaf_name, leaf_parent):
            sharding = None
            if mesh is not None:
                from jax.sharding import NamedSharding

                from fasttalk_tpu.parallel.sharding import _spec_for
                sharding = NamedSharding(
                    mesh, _spec_for(leaf_name, len(shape), shape,
                                    parent=leaf_parent))
            return jax.ShapeDtypeStruct(shape, dt, sharding=sharding)

        if quantize and name == "lm_head":
            # Untied head is stored TRANSPOSED row-quantized
            # ({"qt": int8[V, D], "s": f32[V]} — ops/quant.py
            # _quantize_head_t); the restore target must match or every
            # restart silently repays the full load.
            d, v = sds.shape
            return {
                "qt": with_sharding((v, d), jnp.int8, "qt", name),
                "s": with_sharding((v,), jnp.float32, "s", name),
            }
        if tier == "int4" and name in INT4_LEAVES:
            k, out = sds.shape[-2], sds.shape[-1]
            lead = sds.shape[:-2]
            return {
                "q4": with_sharding(lead + (k // 2, out), jnp.uint8,
                                    "q4", name),
                "s": with_sharding(lead + (k // int(group), out),
                                   jnp.float32, "s", name),
            }
        if quantize and name in QUANTIZED_LEAVES:
            out = sds.shape[-1]
            lead = sds.shape[:-2]
            return {
                "q": with_sharding(sds.shape, jnp.int8, "q", name),
                "s": with_sharding(lead + (out,), jnp.float32, "s", name),
            }
        if quantize and name == "embed":
            return {
                "q": with_sharding(sds.shape, jnp.int8, "q", name),
                "s": with_sharding(sds.shape[:-1], jnp.float32, "s", name),
            }
        return with_sharding(sds.shape, sds.dtype, name, parent)

    return jax.tree_util.tree_map_with_path(to_abstract, shapes)


def save_prepared(params: Any, model_path: str, meta: dict,
                  block: bool = False) -> str | None:
    """Write the engine-ready pytree; best-effort (serving works without
    it — the cache only accelerates the next restart). Serialization
    finishes on a background thread unless ``block`` (tests)."""
    try:
        import orbax.checkpoint as ocp

        path = cache_dir(model_path, meta)
        tmp_ok = os.access(os.path.dirname(os.path.dirname(path))
                           or ".", os.W_OK)
        if not tmp_ok:
            log.warning(f"prepared cache dir not writable: {path}")
            return None
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), params, force=True)

        # Serialization of a large pytree takes as long as the disk
        # write; finish it (and only then publish the meta marker that
        # makes the cache eligible for restore) off the startup path —
        # the cache only helps the NEXT boot, so this boot must not
        # block on it.
        def _finalize() -> None:
            try:
                ckptr.wait_until_finished()
                with open(os.path.join(path, _META), "w") as f:
                    json.dump(meta, f)
                log.info(f"prepared-weight cache written: {path}")
            except Exception as e:  # pragma: no cover - disk races
                log.warning(f"prepared cache finalize failed: {e}")

        if block:
            _finalize()
        else:
            import threading

            # Non-daemon: a short-lived process (bench, smoke run) joins
            # this at exit instead of killing the serialization midway —
            # otherwise the meta marker never lands and every such run
            # repays the full slow load.
            threading.Thread(target=_finalize, name="prepared-cache-save",
                             daemon=False).start()
        return path
    except Exception as e:
        log.warning(f"prepared cache save failed (continuing): {e}")
        return None


def load_prepared(cfg: ModelConfig, model_path: str, dtype,
                  quantize: bool | str, mesh,
                  ckpt_dir: str | None = None,
                  group: int = 128) -> Any | None:
    """Restore the engine-ready pytree, or None when absent/mismatched."""
    meta = cache_meta(cfg, dtype, quantize, mesh, ckpt_dir, group=group)
    path = cache_dir(model_path, meta)
    meta_file = os.path.join(path, _META)
    if not os.path.isfile(meta_file):
        return None
    try:
        with open(meta_file) as f:
            have = json.load(f)
        if have != meta:
            log.warning(f"prepared cache mismatch at {path}; ignoring")
            return None
        import orbax.checkpoint as ocp

        target = abstract_params(cfg, dtype, quantize, mesh, group=group)
        ckptr = ocp.StandardCheckpointer()
        params = ckptr.restore(os.path.abspath(path), target)
        log.info(f"restored prepared weights from {path}")
        return params
    except Exception as e:
        log.warning(f"prepared cache restore failed (reloading): {e}")
        return None
