"""Weight loading: HF safetensors checkpoints → stacked JAX pytrees.

The reference never loads weights in-tree — its external engines pull
them into docker volumes (SURVEY.md §5 checkpoint/resume: none in-tree;
config MODEL_PATH existed at reference config.py:157 but nothing read
it). Here MODEL_PATH points at a HF-format checkpoint directory and the
loader builds the stacked-layer pytree the scan-based forward expects,
optionally placing shards straight onto a device mesh.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from fasttalk_tpu.models.configs import ModelConfig
from fasttalk_tpu.models.llama import Params, init_params
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("models.loader")

# HF parameter name templates → (our pytree path, needs_transpose).
# HF Linear stores [out, in]; our forward uses x @ w so we keep [in, out].
_LAYER_MAP = {
    "model.layers.{i}.input_layernorm.weight": ("attn_norm", False),
    "model.layers.{i}.self_attn.q_proj.weight": ("wq", True),
    "model.layers.{i}.self_attn.k_proj.weight": ("wk", True),
    "model.layers.{i}.self_attn.v_proj.weight": ("wv", True),
    "model.layers.{i}.self_attn.o_proj.weight": ("wo", True),
    "model.layers.{i}.post_attention_layernorm.weight": ("mlp_norm", False),
    "model.layers.{i}.mlp.gate_proj.weight": ("w_gate", True),
    "model.layers.{i}.mlp.up_proj.weight": ("w_up", True),
    "model.layers.{i}.mlp.down_proj.weight": ("w_down", True),
}
# Qwen2-style attention biases, present only when cfg.qkv_bias.
_BIAS_MAP = {
    "model.layers.{i}.self_attn.q_proj.bias": ("bq", False),
    "model.layers.{i}.self_attn.k_proj.bias": ("bk", False),
    "model.layers.{i}.self_attn.v_proj.bias": ("bv", False),
}


def find_checkpoint_dir(model_path: str, model_name: str) -> str | None:
    """Locate a safetensors checkpoint under MODEL_PATH for model_name."""
    candidates = [
        model_path,
        os.path.join(model_path, model_name.replace(":", "_")),
        os.path.join(model_path, model_name.replace(":", "-")),
        # HF-style org/name: flattened (scripts/fetch_model.py layout)
        # or nested as-is.
        os.path.join(model_path,
                     model_name.replace(":", "_").replace("/", "_")),
        os.path.join(model_path, model_name),
    ]
    for c in candidates:
        if os.path.isdir(c) and any(f.endswith(".safetensors")
                                    for f in os.listdir(c)):
            return c
    return None


def _open_all_tensors(ckpt_dir: str) -> dict[str, Any]:
    """Map tensor name → (file handle accessor). Supports sharded index."""
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(ckpt_dir) if f.endswith(".safetensors"))
    index_path = os.path.join(ckpt_dir, "model.safetensors.index.json")
    name_to_file: dict[str, str] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            name_to_file = json.load(f)["weight_map"]
    else:
        for fname in files:
            with safe_open(os.path.join(ckpt_dir, fname), framework="pt") as sf:
                for key in sf.keys():
                    name_to_file[key] = fname
    return name_to_file


def load_params(cfg: ModelConfig, ckpt_dir: str,
                dtype: jnp.dtype = jnp.bfloat16,
                put: Callable[[np.ndarray, str], jax.Array] | None = None,
                ) -> Params:
    """Load a HF Llama checkpoint into the stacked pytree.

    ``put(host_array, pytree_path) -> jax.Array`` lets the caller place
    each tensor with a sharding (parallel/sharding.py provides one);
    default is plain device_put.
    """
    from safetensors import safe_open

    name_to_file = _open_all_tensors(ckpt_dir)
    handles: dict[str, Any] = {}

    def get(name: str) -> np.ndarray:
        # framework="pt": the numpy framework cannot represent bf16 (raises
        # TypeError), and real HF Llama checkpoints are stored bf16.
        import torch

        fname = name_to_file[name]
        if fname not in handles:
            handles[fname] = safe_open(os.path.join(ckpt_dir, fname),
                                       framework="pt")
        t = handles[fname].get_tensor(name)
        if t.dtype == torch.bfloat16:
            t = t.to(torch.float32)
        return t.numpy()

    if put is None:
        def put(arr: np.ndarray, path: str) -> jax.Array:  # noqa: ARG001
            return jax.device_put(jnp.asarray(arr, dtype))

    def cast(a: np.ndarray) -> np.ndarray:
        return np.asarray(a, np.float32)

    params: Params = {
        "embed": put(cast(get("model.embed_tokens.weight")), "embed"),
        "final_norm": put(cast(get("model.norm.weight")), "final_norm"),
        "layers": {},
    }
    layer_map = dict(_LAYER_MAP)
    if cfg.qkv_bias:
        layer_map.update(_BIAS_MAP)
    for tmpl, (path, transpose) in layer_map.items():
        stacked = []
        for i in range(cfg.num_layers):
            t = cast(get(tmpl.format(i=i)))
            stacked.append(t.T if transpose else t)
        params["layers"][path] = put(np.stack(stacked), f"layers/{path}")
    if not cfg.tie_embeddings:
        params["lm_head"] = put(cast(get("lm_head.weight")).T, "lm_head")
    for h in handles.values():
        h.__exit__(None, None, None)
    log.info(f"Loaded checkpoint from {ckpt_dir}", model=cfg.name)
    return params


def init_params_device(cfg: ModelConfig, dtype: jnp.dtype = jnp.bfloat16,
                       mesh=None, quantize: bool | str = False,
                       seed: int = 0,
                       weight_quant_group: int = 128) -> Params:
    """Architecture-faithful random init generated ON the device(s),
    one jitted program per leaf — zero host->device weight transfer,
    which matters both for multi-chip placement (each leaf materialises
    directly in its TP shards) and for weight-free benchmarking over a
    slow host link (host-initialising an 8B model ships gigabytes
    through the relay; this ships one RNG key). ``quantize``
    int8-quantizes matmul leaves inside the same per-leaf program,
    layer by layer, so the f32 generation buffer never exceeds one
    layer slice (see the peak-memory note below). It also accepts a
    tier string — "none"/"off" | "int8" (== True) | "int4", the
    WEIGHT_QUANT surface; int4 packs the seven layer matmuls group-wise
    (``weight_quant_group``; fasttalk_tpu/quantization/int4.py) while
    the embedding/lm_head keep their int8 per-row formats.
    """
    import zlib

    from fasttalk_tpu.ops.quant import QUANTIZED_LEAVES
    from fasttalk_tpu.quantization.int4 import INT4_LEAVES

    tier = (quantize if isinstance(quantize, str)
            else ("int8" if quantize else "none"))
    tier = {"off": "none", "": "none"}.get(tier, tier)
    weight_quant_group = int(weight_quant_group)

    shapes = jax.eval_shape(
        lambda: init_params(cfg, jax.random.PRNGKey(seed), dtype))

    # One jitted program PER LEAF, with layer-stacked leaves filled by a
    # fori_loop writing into a donated accumulator. A single all-leaves
    # program (the previous design) let XLA schedule several leaves'
    # f32 generation buffers live at once — for an 8B model one stacked
    # MLP leaf alone is a 7.5 GB f32 temporary, and the combined peak
    # OOMed a 16 GiB chip before serving ever started. Per-leaf programs
    # bound the peak to (committed leaves so far) + one layer slice;
    # rbg keys keep each compile small, repeated shapes hit the jit
    # cache, and dispatches are async so the relay round trip is paid
    # ~once, not per leaf.
    def _gen_leaf(base_key, crc, *, kind, shape, leaf_quantize):
        # leaf_quantize: False | "out" (per-output-channel, matmul
        # weights) | "row" (per-row, the embedding) | "out_t" (the
        # untied lm_head, stored transposed — ops/quant.py
        # _quantize_head_t; same scale math, kernel-streamable layout)
        # | "group" (int4 group-wise + nibble packing, shared math with
        # quantization/int4.py so generated and checkpoint-quantized
        # leaves can never diverge).
        if kind == "ones":
            return jnp.ones(shape, dtype)
        if kind == "zeros":
            return jnp.zeros(shape, dtype)
        key = jax.random.fold_in(base_key, crc)
        fan_in = shape[-2] if len(shape) >= 2 else shape[-1]
        scale = fan_in ** -0.5

        def make_slice(k, sl_shape):
            return jax.random.normal(k, sl_shape, jnp.float32) * scale

        def quantize_f32(wf):
            # Shared math with ops/quant.py so generated and
            # checkpoint-quantized tables are bit-identical.
            from fasttalk_tpu.ops.quant import (quantize_math_out,
                                                quantize_math_row)

            if leaf_quantize == "row":
                return quantize_math_row(wf)
            return quantize_math_out(wf)

        if len(shape) == 3:
            # Layer-stacked: generate one [in, out] f32 slice per layer
            # and write it into the accumulator in place.
            num_layers = shape[0]
            if leaf_quantize == "group":
                from fasttalk_tpu.quantization.int4 import (
                    pack_int4, quantize_math_group)

                def body(layer, acc):
                    accq, accs = acc
                    sl = make_slice(jax.random.fold_in(key, layer),
                                    shape[1:])
                    q, s = quantize_math_group(sl, weight_quant_group)
                    return (accq.at[layer].set(pack_int4(q)),
                            accs.at[layer].set(s))

                accq, accs = jax.lax.fori_loop(
                    0, num_layers, body,
                    (jnp.zeros((shape[0], shape[1] // 2, shape[2]),
                               jnp.uint8),
                     jnp.zeros((shape[0], shape[1] // weight_quant_group,
                                shape[2]), jnp.float32)))
                return {"q4": accq, "s": accs}
            if leaf_quantize:
                def body(layer, acc):
                    accq, accs = acc
                    sl = make_slice(jax.random.fold_in(key, layer),
                                    shape[1:])
                    q, s = quantize_f32(sl)
                    return (accq.at[layer].set(q), accs.at[layer].set(s))

                accq, accs = jax.lax.fori_loop(
                    0, num_layers, body,
                    (jnp.zeros(shape, jnp.int8),
                     jnp.zeros((shape[0], shape[2]), jnp.float32)))
                return {"q": accq, "s": accs}

            def body(layer, acc):
                sl = make_slice(jax.random.fold_in(key, layer), shape[1:])
                return acc.at[layer].set(sl.astype(dtype))

            return jax.lax.fori_loop(0, num_layers, body,
                                     jnp.zeros(shape, dtype))

        wf = make_slice(key, shape)
        if leaf_quantize == "out_t":
            q, s = quantize_f32(wf)  # per-output-channel on [D, V]
            return {"qt": q.T, "s": s}  # identical values, [V, D] layout
        if leaf_quantize:
            q, s = quantize_f32(wf)
            return {"q": q, "s": s}
        return wf.astype(dtype)

    gen_leaf = jax.jit(_gen_leaf,
                       static_argnames=("kind", "shape", "leaf_quantize"))

    # "rbg" (XLA RngBitGenerator), not threefry: threefry over 10^9
    # elements compiles ~4x slower. rbg is also the JAX-recommended impl
    # for sharded generation (no cross-device communication). Weight-
    # free init only feeds tests and benchmarks, so RNG quality is not
    # load-bearing.
    base_key = jax.random.key(seed, impl="rbg")

    # Mesh-path jit wrappers memoized by their output sharding: a fresh
    # jax.jit per leaf would re-trace/re-compile repeated shapes (the
    # seven layer-stacked leaves mostly share them).
    _sharded_fns: dict[Any, Any] = {}

    def _sharded_gen(out_sh):
        key = (tuple(sorted(out_sh.items())) if isinstance(out_sh, dict)
               else out_sh)
        fn = _sharded_fns.get(key)
        if fn is None:
            fn = jax.jit(_gen_leaf,
                         static_argnames=("kind", "shape", "leaf_quantize"),
                         out_shardings=out_sh)
            _sharded_fns[key] = fn
        return fn

    def gen(path, sds):
        name = str(getattr(path[-1], "key", path[-1]))
        shape = sds.shape
        if "norm" in name:
            kind = "ones"
        elif name in ("bq", "bk", "bv"):
            kind = "zeros"
        else:
            kind = "normal"
        leaf_quantize: bool | str = False
        if tier != "none" and kind == "normal":
            if name == "lm_head":
                leaf_quantize = "out_t"
            elif tier == "int4" and name in INT4_LEAVES:
                leaf_quantize = "group"
            elif name in QUANTIZED_LEAVES:
                leaf_quantize = "out"
            elif name == "embed":
                leaf_quantize = "row"
        # crc32, not hash(): Python's hash is salted per process, which
        # would give each host of a multi-host slice different weights
        # for the same leaf (and break same-seed reproducibility).
        full = "/".join(str(getattr(k, "key", k)) for k in path)
        crc = zlib.crc32(full.encode()) & 0x7FFFFFFF
        fn = gen_leaf
        if mesh is not None:
            from jax.sharding import NamedSharding

            from fasttalk_tpu.parallel.sharding import (_parent_name,
                                                        _spec_for)

            if leaf_quantize == "group":
                qshape = shape[:-2] + (shape[-2] // 2, shape[-1])
                s_shape = shape[:-2] + (
                    shape[-2] // weight_quant_group, shape[-1])
                out_sh = {
                    "q4": NamedSharding(mesh, _spec_for(
                        "q4", len(qshape), qshape, parent=name)),
                    "s": NamedSharding(mesh, _spec_for(
                        "s", len(s_shape), s_shape, parent=name)),
                }
            elif leaf_quantize:
                s_shape = (shape[:-1] if leaf_quantize == "row"
                           else shape[:-2] + shape[-1:])
                qname = "qt" if leaf_quantize == "out_t" else "q"
                qshape = (shape[::-1] if leaf_quantize == "out_t"
                          else shape)
                out_sh = {
                    qname: NamedSharding(mesh, _spec_for(
                        qname, len(qshape), qshape, parent=name)),
                    "s": NamedSharding(mesh, _spec_for(
                        "s", len(s_shape), s_shape, parent=name)),
                }
            else:
                out_sh = NamedSharding(
                    mesh, _spec_for(name, len(shape), shape,
                                    parent=_parent_name(path)))
            fn = _sharded_gen(out_sh)
        return fn(base_key, crc, kind=kind, shape=shape,
                  leaf_quantize=leaf_quantize)

    params = jax.tree_util.tree_map_with_path(gen, shapes)
    log.info(f"Random-initialised {cfg.name} on device "
             f"({tier if tier != 'none' else jnp.dtype(dtype).name}"
             f"{', sharded' if mesh is not None else ''})")
    return params


def load_or_init(cfg: ModelConfig, model_path: str,
                 dtype: jnp.dtype = jnp.bfloat16,
                 put: Callable[[np.ndarray, str], jax.Array] | None = None,
                 seed: int = 0, mesh=None,
                 quantize: bool = False) -> tuple[Params, bool]:
    """Load weights if a checkpoint exists under model_path, else random
    init (architecture-faithful; used for tests and weight-free perf work).

    ``put`` applies to the checkpoint-streaming path. The random path
    routes through init_params_device when ``mesh``/``quantize`` is
    given (direct-to-shard, no host->device weight transfer) — a bare
    ``put`` cannot express those semantics, so passing put without a
    checkpoint is rejected rather than silently ignored.

    Returns (params, loaded_from_checkpoint).
    """
    ckpt = find_checkpoint_dir(model_path, cfg.name) if model_path else None
    if ckpt:
        return load_params(cfg, ckpt, dtype, put), True
    log.warning(
        f"No checkpoint for {cfg.name!r} under {model_path!r}; "
        "using random-initialised weights")
    if put is not None:
        raise ValueError(
            "load_or_init: no checkpoint found and `put` cannot drive "
            "random init — pass mesh=/quantize= (routed through "
            "init_params_device) instead")
    if mesh is not None or quantize:
        return init_params_device(cfg, dtype, mesh=mesh,
                                  quantize=quantize, seed=seed), False
    return init_params(cfg, jax.random.PRNGKey(seed), dtype), False
