"""Weight loading: HF safetensors checkpoints → stacked JAX pytrees.

The reference never loads weights in-tree — its external engines pull
them into docker volumes (SURVEY.md §5 checkpoint/resume: none in-tree;
config MODEL_PATH existed at reference config.py:157 but nothing read
it). Here MODEL_PATH points at a HF-format checkpoint directory and the
loader builds the stacked-layer pytree the scan-based forward expects,
optionally placing shards straight onto a device mesh.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np

from fasttalk_tpu.models.configs import ModelConfig
from fasttalk_tpu.models.llama import Params, init_params
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("models.loader")

# HF parameter name templates → (our pytree path, needs_transpose).
# HF Linear stores [out, in]; our forward uses x @ w so we keep [in, out].
_LAYER_MAP = {
    "model.layers.{i}.input_layernorm.weight": ("attn_norm", False),
    "model.layers.{i}.self_attn.q_proj.weight": ("wq", True),
    "model.layers.{i}.self_attn.k_proj.weight": ("wk", True),
    "model.layers.{i}.self_attn.v_proj.weight": ("wv", True),
    "model.layers.{i}.self_attn.o_proj.weight": ("wo", True),
    "model.layers.{i}.post_attention_layernorm.weight": ("mlp_norm", False),
    "model.layers.{i}.mlp.gate_proj.weight": ("w_gate", True),
    "model.layers.{i}.mlp.up_proj.weight": ("w_up", True),
    "model.layers.{i}.mlp.down_proj.weight": ("w_down", True),
}
# Qwen2-style attention biases, present only when cfg.qkv_bias.
_BIAS_MAP = {
    "model.layers.{i}.self_attn.q_proj.bias": ("bq", False),
    "model.layers.{i}.self_attn.k_proj.bias": ("bk", False),
    "model.layers.{i}.self_attn.v_proj.bias": ("bv", False),
}


def find_checkpoint_dir(model_path: str, model_name: str) -> str | None:
    """Locate a safetensors checkpoint under MODEL_PATH for model_name."""
    candidates = [
        model_path,
        os.path.join(model_path, model_name.replace(":", "_")),
        os.path.join(model_path, model_name.replace(":", "-")),
        os.path.join(model_path, model_name),
    ]
    for c in candidates:
        if os.path.isdir(c) and any(f.endswith(".safetensors")
                                    for f in os.listdir(c)):
            return c
    return None


def _open_all_tensors(ckpt_dir: str) -> dict[str, Any]:
    """Map tensor name → (file handle accessor). Supports sharded index."""
    from safetensors import safe_open

    files = sorted(f for f in os.listdir(ckpt_dir) if f.endswith(".safetensors"))
    index_path = os.path.join(ckpt_dir, "model.safetensors.index.json")
    name_to_file: dict[str, str] = {}
    if os.path.exists(index_path):
        with open(index_path) as f:
            name_to_file = json.load(f)["weight_map"]
    else:
        for fname in files:
            with safe_open(os.path.join(ckpt_dir, fname), framework="pt") as sf:
                for key in sf.keys():
                    name_to_file[key] = fname
    return name_to_file


def load_params(cfg: ModelConfig, ckpt_dir: str,
                dtype: jnp.dtype = jnp.bfloat16,
                put: Callable[[np.ndarray, str], jax.Array] | None = None,
                ) -> Params:
    """Load a HF Llama checkpoint into the stacked pytree.

    ``put(host_array, pytree_path) -> jax.Array`` lets the caller place
    each tensor with a sharding (parallel/sharding.py provides one);
    default is plain device_put.
    """
    from safetensors import safe_open

    name_to_file = _open_all_tensors(ckpt_dir)
    handles: dict[str, Any] = {}

    def get(name: str) -> np.ndarray:
        # framework="pt": the numpy framework cannot represent bf16 (raises
        # TypeError), and real HF Llama checkpoints are stored bf16.
        import torch

        fname = name_to_file[name]
        if fname not in handles:
            handles[fname] = safe_open(os.path.join(ckpt_dir, fname),
                                       framework="pt")
        t = handles[fname].get_tensor(name)
        if t.dtype == torch.bfloat16:
            t = t.to(torch.float32)
        return t.numpy()

    if put is None:
        def put(arr: np.ndarray, path: str) -> jax.Array:  # noqa: ARG001
            return jax.device_put(jnp.asarray(arr, dtype))

    def cast(a: np.ndarray) -> np.ndarray:
        return np.asarray(a, np.float32)

    params: Params = {
        "embed": put(cast(get("model.embed_tokens.weight")), "embed"),
        "final_norm": put(cast(get("model.norm.weight")), "final_norm"),
        "layers": {},
    }
    layer_map = dict(_LAYER_MAP)
    if cfg.qkv_bias:
        layer_map.update(_BIAS_MAP)
    for tmpl, (path, transpose) in layer_map.items():
        stacked = []
        for i in range(cfg.num_layers):
            t = cast(get(tmpl.format(i=i)))
            stacked.append(t.T if transpose else t)
        params["layers"][path] = put(np.stack(stacked), f"layers/{path}")
    if not cfg.tie_embeddings:
        params["lm_head"] = put(cast(get("lm_head.weight")).T, "lm_head")
    for h in handles.values():
        h.__exit__(None, None, None)
    log.info(f"Loaded checkpoint from {ckpt_dir}", model=cfg.name)
    return params


def load_or_init(cfg: ModelConfig, model_path: str,
                 dtype: jnp.dtype = jnp.bfloat16,
                 put: Callable[[np.ndarray, str], jax.Array] | None = None,
                 seed: int = 0) -> tuple[Params, bool]:
    """Load weights if a checkpoint exists under model_path, else random
    init (architecture-faithful; used for tests and weight-free perf work).

    Returns (params, loaded_from_checkpoint).
    """
    ckpt = find_checkpoint_dir(model_path, cfg.name) if model_path else None
    if ckpt:
        return load_params(cfg, ckpt, dtype, put), True
    log.warning(
        f"No checkpoint for {cfg.name!r} under {model_path!r}; "
        "using random-initialised weights")
    params = init_params(cfg, jax.random.PRNGKey(seed), dtype)
    if put is not None:
        params = jax.tree_util.tree_map_with_path(
            lambda path, a: put(np.asarray(a),
                                "/".join(str(getattr(k, "key", k)) for k in path)),
            params)
    return params, False
