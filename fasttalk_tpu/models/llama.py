"""Pure-functional Llama forward pass with in-forward KV cache update.

TPU-first design notes:
- Per-layer weights are *stacked* along a leading layer axis and the
  transformer body is a single ``lax.scan`` — one traced layer instead of
  N, so a 70B/80-layer model compiles as fast as the 1B.
- The KV cache is threaded through the scan as scan inputs/outputs with
  matching shapes, so under ``jit(..., donate_argnums=...)`` XLA aliases
  the buffers and decode updates the cache in place in HBM.
- All norms/softmax/rope run in float32; matmuls stay in bfloat16 on the
  MXU (``preferred_element_type`` on the attention contraction).
- Writes use vmapped ``dynamic_update_slice`` so each batch row (slot)
  can write at its own position — the primitive continuous batching needs.

This module replaces the model execution that the reference delegated to
external vLLM/Ollama containers (SURVEY.md §2: in-tree native components
NONE; engine capability lived in the containers).
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from fasttalk_tpu.models.configs import ModelConfig
from fasttalk_tpu.ops.attention import (attend, attend_blockwise,
                                        gather_paged_rows,
                                        paged_gather_indices)
from fasttalk_tpu.ops.kv_quant import kv_dequantize, kv_quantize
from fasttalk_tpu.ops.quant import embed_lookup, matmul_tied
from fasttalk_tpu.ops.quant import matmul as qmm
from fasttalk_tpu.ops.rope import apply_rope, rope_frequencies

Params = dict[str, Any]


class KVCache(NamedTuple):
    """Per-layer key/value cache: k, v each [L, B, S, num_kv_heads, head_dim].

    Quantized tier (``KV_QUANT=int8``, ops/kv_quant.py): k/v are int8
    and ``k_scale``/``v_scale`` hold per-row float32 scales
    [L, B, S, G] (G = 1 per-token or num_kv_heads per-head). ``None``
    scales mean the full-precision cache; every consumer branches on
    that at trace time, and None fields are empty pytree nodes, so the
    two layouts jit/scan/donate identically.
    """

    k: jnp.ndarray
    v: jnp.ndarray
    k_scale: jnp.ndarray | None = None
    v_scale: jnp.ndarray | None = None

    @property
    def batch(self) -> int:
        return self.k.shape[1]

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_cache(cfg: ModelConfig, batch: int, max_len: int,
               dtype: jnp.dtype = jnp.bfloat16, device=None, *,
               quantized: bool = False,
               scale_granule: int = 1) -> KVCache:
    """``device`` may be a Sharding — the cache is then created directly
    in its shards (never materialised on a single chip).

    ``quantized`` allocates the int8 tier: int8 rows + float32 scales
    with granule axis ``scale_granule`` (1 or num_kv_heads). Zero
    scales on the unwritten tail dequantize to the same zeros the bf16
    cache initialises to (and are never attended anyway)."""
    shape = (cfg.num_layers, batch, max_len, cfg.num_kv_heads, cfg.head_dim)
    if quantized:
        sshape = (cfg.num_layers, batch, max_len, scale_granule)
        return KVCache(k=jnp.zeros(shape, jnp.int8, device=device),
                       v=jnp.zeros(shape, jnp.int8, device=device),
                       k_scale=jnp.zeros(sshape, jnp.float32,
                                         device=device),
                       v_scale=jnp.zeros(sshape, jnp.float32,
                                         device=device))
    return KVCache(k=jnp.zeros(shape, dtype, device=device),
                   v=jnp.zeros(shape, dtype, device=device))


def init_paged_cache(cfg: ModelConfig, num_blocks: int, block_size: int,
                     dtype: jnp.dtype = jnp.bfloat16, *,
                     quantized: bool = False,
                     scale_granule: int = 1) -> KVCache:
    """The paged tier's device pool (KV_LAYOUT=paged, docs/KVCACHE.md):
    one FLAT row pool per layer, ``[L, num_blocks * block_size, Kv,
    H]``, with no slot axis — slots map logical positions onto pool
    rows through host-managed block tables (kvcache/blocks.py), so a
    chip's admission capacity is priced at blocks actually in use, not
    every slot's worst-case context. Distinguishable from the dense
    layout by rank (4-D pool vs 5-D ``[L, B, S, Kv, H]``); the same
    NamedTuple rides every donated call chain unchanged. The quantized
    tier stores int8 rows + per-row float32 scales ``[L, P, G]`` —
    scales live in pool layout too (the "per-block-row" arrays), so
    aliasing/park/restore move rows and scales together."""
    p = num_blocks * block_size
    shape = (cfg.num_layers, p, cfg.num_kv_heads, cfg.head_dim)
    if quantized:
        sshape = (cfg.num_layers, p, scale_granule)
        return KVCache(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
    return KVCache(k=jnp.zeros(shape, dtype),
                   v=jnp.zeros(shape, dtype))


def init_params(cfg: ModelConfig, rng: jax.Array,
                dtype: jnp.dtype = jnp.bfloat16) -> Params:
    """Random init with GPT-style scaled normals (for tests and weight-free
    benchmarking; real checkpoints come from models/loader.py)."""
    keys = iter(jax.random.split(rng, 16))
    d, f, l = cfg.hidden_size, cfg.intermediate_size, cfg.num_layers

    def normal(key, shape, scale):
        return (jax.random.normal(key, shape, jnp.float32) * scale).astype(dtype)

    scale = d ** -0.5
    params: Params = {
        "embed": normal(next(keys), (cfg.vocab_size, d), scale),
        "layers": {
            "attn_norm": jnp.ones((l, d), dtype),
            "wq": normal(next(keys), (l, d, cfg.q_dim), scale),
            "wk": normal(next(keys), (l, d, cfg.kv_dim), scale),
            "wv": normal(next(keys), (l, d, cfg.kv_dim), scale),
            "wo": normal(next(keys), (l, cfg.q_dim, d), scale / np.sqrt(2 * l)),
            "mlp_norm": jnp.ones((l, d), dtype),
            "w_gate": normal(next(keys), (l, d, f), scale),
            "w_up": normal(next(keys), (l, d, f), scale),
            "w_down": normal(next(keys), (l, f, d), f ** -0.5 / np.sqrt(2 * l)),
        },
        "final_norm": jnp.ones((d,), dtype),
    }
    if cfg.qkv_bias:  # Qwen2-style attention biases
        params["layers"]["bq"] = jnp.zeros((l, cfg.q_dim), dtype)
        params["layers"]["bk"] = jnp.zeros((l, cfg.kv_dim), dtype)
        params["layers"]["bv"] = jnp.zeros((l, cfg.kv_dim), dtype)
    if not cfg.tie_embeddings:
        params["lm_head"] = normal(next(keys), (d, cfg.vocab_size), scale)
    return params


def rms_norm(x: jnp.ndarray, w: jnp.ndarray, eps: float) -> jnp.ndarray:
    xf = x.astype(jnp.float32)
    normed = xf * jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    return (normed * w.astype(jnp.float32)).astype(x.dtype)


def _write_kv(cache_layer: jnp.ndarray, new: jnp.ndarray,
              write_start: jnp.ndarray,
              write_mask: jnp.ndarray | None) -> jnp.ndarray:
    """Write new [B, T, ...] into cache [B, S, ...] at per-row offsets
    (trailing dims pass through — [K, H] row blocks and [G] scale rows
    share this one write path).

    ``write_mask`` [B] bool: rows with False keep their existing cache
    contents (used by the batched decode step so idle slots can never
    clobber resident KV of a parked session).
    """
    zeros = (0,) * (new.ndim - 2)
    if write_mask is None:
        def row(c, n, s):
            return jax.lax.dynamic_update_slice(c, n, (s,) + zeros)
        return jax.vmap(row)(cache_layer, new, write_start)

    def row(c, n, s, m):
        cur = jax.lax.dynamic_slice(c, (s,) + zeros, n.shape)
        return jax.lax.dynamic_update_slice(c, jnp.where(m, n, cur),
                                            (s,) + zeros)
    return jax.vmap(row)(cache_layer, new, write_start, write_mask)


def forward(params: Params, cfg: ModelConfig, tokens: jnp.ndarray,
            positions: jnp.ndarray, cache: KVCache, write_start: jnp.ndarray,
            *, blockwise: bool = False,
            write_mask: jnp.ndarray | None = None,
            pallas_decode: bool = False,
            pallas_int8: bool = False,
            pallas_int4: bool = False,
            logits_indices: jnp.ndarray | None = None,
            attn_override: Any = None,
            override_write: bool = False,
            cache_attn_override: Any = None,
            ) -> tuple[jnp.ndarray, KVCache]:
    """Run the transformer over ``tokens`` [B, T], updating the cache.

    positions [B, T]: absolute position of each token (also its RoPE phase
    and attention horizon). write_start [B]: cache index where this chunk's
    K/V are written per row. write_mask [B] (optional): rows with False
    leave the cache untouched. Works for prefill (T=chunk) and decode
    (T=1) alike; ``blockwise`` picks the flash-style attention for long
    chunks, ``pallas_decode`` the length-pruning Pallas kernel for T=1
    (single-device only — see ops/pallas_attention.py).

    ``logits_indices`` [B] (optional): project the lm_head for ONE
    position per row instead of the whole chunk. Prefill only consumes
    the last token's logits, and skipping the rest avoids both the
    [B, T, vocab] logits buffer and — for int8 tied embeddings — an XLA
    dequant that would materialise the full bf16 table per chunk; the
    returned logits are [B, 1, vocab].

    ``attn_override`` (optional): ``fn(q, k, v, positions) -> o`` over
    the freshly computed q/k/v of the whole block, replacing the
    cache-read attention — the full-self-attention regime (T == the
    whole sequence). This is how parallel/ring_attention.py plugs in:
    K/V rotate over the "sp" ICI ring instead of being all-gathered,
    so per-chip sequence memory is O(T/sp). Cache writes are skipped
    by default (training passes a dummy cache); ``override_write=True``
    additionally writes the fresh K/V into the cache — the serving
    ring-prefill regime, where decode must later read what the ring
    attended over.

    ``cache_attn_override`` (optional): ``fn(q, ck, cv, positions) ->
    o`` replacing the CACHE-READ attention (writes still happen) —
    how parallel.ring_attention.decode_attention_sharded plugs in for
    sp-sharded serving decode: per-chip folds over the local KV shard
    plus a statistics psum, instead of GSPMD's per-step K/V
    all-gather.

    Returns (logits [B, T, vocab], updated cache). (The decode hot path
    is ``forward_decode`` below — scatter cache writes + bounded
    attention reads; this function serves prefill, training, and the
    TP/mesh decode.)
    """
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                            cfg.rope_scaling))
    x = embed_lookup(params["embed"], tokens,
                     params["final_norm"].dtype)
    b, t = tokens.shape
    # The int8 dequant-fused matmul kernel applies in the single-device
    # T=1 decode regime; its gate (pallas_int8) is independent of the
    # attention kernel's (pallas_decode) — disabling one must not
    # silently disable the other.
    pok = pallas_int8 and t == 1
    # Same regime for the int4 dequant-fused kernel (gated separately:
    # TPU_USE_PALLAS_INT4); on {"q4","s"} leaves qmm's XLA path unpacks
    # nibbles inline, so pok4=False still never materialises f32.
    pok4 = pallas_int4 and t == 1
    # Int8 KV tier: quantize each fresh row at write time, dequantize
    # on the attention read — fused into the operand load on the XLA
    # path (ops/kv_quant.py), or inside the Pallas kernel after the
    # DMA (ops/pallas_attention.py: int8 bytes cross HBM either way).
    # The self-attention override regimes (ring prefill, training)
    # bypass the cache read and are rejected at Config validation.
    kvq = cache.quantized
    if kvq:
        assert attn_override is None, \
            "quantized KV cache: self-attention override regimes " \
            "bypass the cache read"
        kvg = cache.k_scale.shape[-1]

    def layer(x, scanned):
        lp, ck, cv, ks, vs = scanned
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        q, k, v = (qmm(h, lp["wq"], pok, pok4), qmm(h, lp["wk"], pok, pok4),
                   qmm(h, lp["wv"], pok, pok4))
        if cfg.qkv_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(b, t, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        q = apply_rope(q, positions, inv_freq)
        k = apply_rope(k, positions, inv_freq)
        if attn_override is not None:
            if override_write:
                ck = _write_kv(ck, k, write_start, write_mask)
                cv = _write_kv(cv, v, write_start, write_mask)
            o = attn_override(q, k, v, positions)
        else:
            if kvq:
                qk, sk = kv_quantize(k, kvg)
                qv, sv = kv_quantize(v, kvg)
                ck = _write_kv(ck, qk, write_start, write_mask)
                cv = _write_kv(cv, qv, write_start, write_mask)
                ks = _write_kv(ks, sk, write_start, write_mask)
                vs = _write_kv(vs, sv, write_start, write_mask)
            else:
                ck = _write_kv(ck, k, write_start, write_mask)
                cv = _write_kv(cv, v, write_start, write_mask)
            if pallas_decode and t == 1 and cache_attn_override is None:
                from fasttalk_tpu.ops.pallas_attention import decode_attend

                # Quantized tier: int8 rows + scales go straight into
                # the kernel — no materialised bf16 dequant buffer.
                o = decode_attend(q[:, 0], ck, cv, positions[:, 0] + 1,
                                  k_scale=ks if kvq else None,
                                  v_scale=vs if kvq else None)[:, None]
            else:
                if kvq:
                    ak = kv_dequantize(ck, ks, x.dtype)
                    av = kv_dequantize(cv, vs, x.dtype)
                else:
                    ak, av = ck, cv
                if cache_attn_override is not None:
                    o = cache_attn_override(q, ak, av, positions)
                else:
                    attn_fn = attend_blockwise if blockwise else attend
                    o = attn_fn(q, ak, av, positions)
        x = x + qmm(o.reshape(b, t, cfg.q_dim), lp["wo"], pok, pok4)
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        gate = jax.nn.silu(
            qmm(h, lp["w_gate"], pok, pok4).astype(jnp.float32))
        up = qmm(h, lp["w_up"], pok, pok4).astype(jnp.float32)
        x = x + qmm((gate * up).astype(x.dtype), lp["w_down"], pok, pok4)
        return x, (ck, cv, ks, vs)

    x, (new_k, new_v, new_ks, new_vs) = jax.lax.scan(
        layer, x, (params["layers"], cache.k, cache.v,
                   cache.k_scale, cache.v_scale))
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    pok_head = pok
    if logits_indices is not None:
        x = jnp.take_along_axis(
            x, logits_indices.astype(jnp.int32)[:, None, None], axis=1)
        pok_head = pallas_int8  # single row: the T=1 kernels apply
    if cfg.tie_embeddings:
        logits = matmul_tied(x, params["embed"],
                             pok_head).astype(jnp.float32)
    else:
        logits = qmm(x, params["lm_head"], pok_head).astype(jnp.float32)
    return logits, KVCache(k=new_k, v=new_v, k_scale=new_ks,
                           v_scale=new_vs)


def forward_decode_multi(params: Params, cfg: ModelConfig,
                         tokens: jnp.ndarray, positions: jnp.ndarray,
                         cache: KVCache, write_mask: jnp.ndarray, *,
                         attn_len: int, pallas_int8: bool = False,
                         pallas_int4: bool = False,
                         block_table: jnp.ndarray | None = None,
                         block_size: int = 0,
                         pallas_paged: bool = False,
                         pallas_dense: bool = False,
                         ) -> tuple[jnp.ndarray, KVCache]:
    """Scatter-write decode over a short block: tokens [B, T] ->
    logits [B, T, V], cache updated IN PLACE.

    The whole cache rides the layer scan's carry (carries alias under
    donation), each layer scatter-writes only the block's K/V columns
    ([B, T, Kv, H] — KiB, not the bucket), and attention reads a
    per-layer dynamic-slice bounded by the static ``attn_len``. T=1 is
    the plain decode step (``forward_decode`` below); T>1 is the
    speculative-decoding verify block (engine/spec: current token +
    draft), causal within the block via absolute-position masking.

    positions [B]: absolute position of tokens[:, 0] per slot (the
    block occupies positions..positions+T-1). write_mask [B]: rows with
    False neither write the cache nor advance (their scatter is clamped
    out of range and dropped).

    ``block_table`` [B, attn_len // block_size] selects the PAGED tier
    (KV_LAYOUT=paged): the cache is then the flat block pool
    ``[L, P, Kv, H]`` (init_paged_cache) and every logical position
    routes through the table — writes scatter to
    ``table[b, pos // bs] * bs + pos % bs`` and the attention read
    gathers the slot's blocks into position order
    (ops/attention.paged_gather_indices, the XLA gather fallback).
    ``pallas_paged`` replaces that gather+attend with the block-walking
    Pallas kernel; ``pallas_dense`` routes the dense slice read through
    the length-pruning kernel instead of ``attend``. Both handle T>1
    (spec-verify blocks) and the int8 tier (the kernels take the int8
    rows + scale arrays and dequantize after the DMA — see
    ops/pallas_attention.py).
    """
    inv_freq = jnp.asarray(rope_frequencies(cfg.head_dim, cfg.rope_theta,
                                            cfg.rope_scaling))
    x = embed_lookup(params["embed"], tokens,
                     params["final_norm"].dtype)  # [B, T, D]
    b, t = tokens.shape
    paged = block_table is not None
    pos_mat = positions[:, None] + jnp.arange(t)[None, :]  # [B, T]
    rows = jnp.arange(b)
    if paged:
        assert block_table.shape[1] * block_size == attn_len, \
            "block table must cover exactly the attn_len bucket"
        pool_rows = cache.k.shape[1]
        # Logical position -> flat pool row, via the table. Masked rows
        # scatter out of range — DISTINCT per (row, column), because
        # unique_indices below promises no duplicates even among
        # dropped entries.
        blk = pos_mat // block_size
        flat = (jnp.take_along_axis(block_table, blk, axis=1)
                * block_size + pos_mat % block_size)
        oob = (pool_rows + rows[:, None] * t
               + jnp.arange(t)[None, :])
        write_cols = jnp.where(write_mask[:, None], flat, oob)
        # The attention-read gather indices are table-only (constant
        # over the layer scan): rows land in logical position order,
        # so the absolute-position mask in attend() is unchanged.
        gather_idx = paged_gather_indices(block_table, block_size)
    else:
        s_total = cache.max_len
        # Masked rows scatter out of range -> dropped (mode="drop").
        write_cols = jnp.where(write_mask[:, None], pos_mat, s_total)
    # Int8 KV tier: the block's fresh rows quantize before the scatter
    # (per-row max-abs scales, ops/kv_quant.py), and the bounded
    # attention read dequantizes the sliced region into the matmul —
    # int8 bytes are what the decode step streams from HBM.
    kvq = cache.quantized
    kvg = cache.k_scale.shape[-1] if kvq else 0

    def layer(carry, lp):
        x, ck_all, cv_all, ks_all, vs_all, li = carry
        h = rms_norm(x, lp["attn_norm"], cfg.rms_eps)
        # The T=1 kernels self-gate on shape inside qmm (x.shape[1]==1),
        # so the spec-decode verify block (T>1) transparently takes the
        # XLA dequant paths with the same flags.
        pok, pok4 = pallas_int8, pallas_int4
        q, k, v = (qmm(h, lp["wq"], pok, pok4), qmm(h, lp["wk"], pok, pok4),
                   qmm(h, lp["wv"], pok, pok4))
        if cfg.qkv_bias:
            q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
        q = q.reshape(b, t, cfg.num_heads, cfg.head_dim)
        k = k.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        v = v.reshape(b, t, cfg.num_kv_heads, cfg.head_dim)
        q = apply_rope(q, pos_mat, inv_freq)
        k = apply_rope(k, pos_mat, inv_freq)
        if kvq:
            k, sk = kv_quantize(k, kvg)
            v, sv = kv_quantize(v, kvg)
            if paged:
                ks_all = ks_all.at[li, write_cols].set(
                    sk, mode="drop", unique_indices=True)
                vs_all = vs_all.at[li, write_cols].set(
                    sv, mode="drop", unique_indices=True)
            else:
                ks_all = ks_all.at[li, rows[:, None], write_cols].set(
                    sk, mode="drop", unique_indices=True)
                vs_all = vs_all.at[li, rows[:, None], write_cols].set(
                    sv, mode="drop", unique_indices=True)
        if paged:
            # Flat-pool scatter: [B, T] rows land at their table-mapped
            # pool rows; the read below gathers them back into logical
            # position order.
            ck_all = ck_all.at[li, write_cols].set(
                k, mode="drop", unique_indices=True)
            cv_all = cv_all.at[li, write_cols].set(
                v, mode="drop", unique_indices=True)
            lk = jax.lax.dynamic_slice(
                ck_all, (li, 0, 0, 0),
                (1, pool_rows, cfg.num_kv_heads, cfg.head_dim))[0]
            lv = jax.lax.dynamic_slice(
                cv_all, (li, 0, 0, 0),
                (1, pool_rows, cfg.num_kv_heads, cfg.head_dim))[0]
            if pallas_paged:
                from fasttalk_tpu.ops.pallas_attention import \
                    decode_attend_paged

                lks = lvs = None
                if kvq:
                    lks = jax.lax.dynamic_slice(
                        ks_all, (li, 0, 0), (1, pool_rows, kvg))[0]
                    lvs = jax.lax.dynamic_slice(
                        vs_all, (li, 0, 0), (1, pool_rows, kvg))[0]
                o = decode_attend_paged(
                    q, lk, lv, pos_mat[:, -1] + 1, block_table,
                    block_size=block_size, k_scale=lks, v_scale=lvs)
            else:
                ak = gather_paged_rows(lk, gather_idx)
                av = gather_paged_rows(lv, gather_idx)
                if kvq:
                    aks = gather_paged_rows(jax.lax.dynamic_slice(
                        ks_all, (li, 0, 0), (1, pool_rows, kvg))[0],
                        gather_idx)
                    avs = gather_paged_rows(jax.lax.dynamic_slice(
                        vs_all, (li, 0, 0), (1, pool_rows, kvg))[0],
                        gather_idx)
                    ak = kv_dequantize(ak, aks, x.dtype)
                    av = kv_dequantize(av, avs, x.dtype)
                o = attend(q, ak, av, pos_mat)
        else:
            ck_all = ck_all.at[li, rows[:, None], write_cols].set(
                k, mode="drop", unique_indices=True)
            cv_all = cv_all.at[li, rows[:, None], write_cols].set(
                v, mode="drop", unique_indices=True)
            ak = jax.lax.dynamic_slice(
                ck_all, (li, 0, 0, 0, 0),
                (1, b, attn_len, cfg.num_kv_heads, cfg.head_dim))[0]
            av = jax.lax.dynamic_slice(
                cv_all, (li, 0, 0, 0, 0),
                (1, b, attn_len, cfg.num_kv_heads, cfg.head_dim))[0]
            aks = avs = None
            if kvq:
                aks = jax.lax.dynamic_slice(
                    ks_all, (li, 0, 0, 0), (1, b, attn_len, kvg))[0]
                avs = jax.lax.dynamic_slice(
                    vs_all, (li, 0, 0, 0), (1, b, attn_len, kvg))[0]
            if pallas_dense:
                from fasttalk_tpu.ops.pallas_attention import \
                    decode_attend

                # Length-pruning kernel over the bounded slice; int8
                # rows + scales dequantize inside the kernel, so the
                # bf16 dequant buffer is never materialised.
                o = decode_attend(q, ak, av, pos_mat[:, -1] + 1,
                                  k_scale=aks, v_scale=avs)
            else:
                if kvq:
                    ak = kv_dequantize(ak, aks, x.dtype)
                    av = kv_dequantize(av, avs, x.dtype)
                o = attend(q, ak, av, pos_mat)
        x = x + qmm(o.reshape(b, t, cfg.q_dim), lp["wo"], pok, pok4)
        h = rms_norm(x, lp["mlp_norm"], cfg.rms_eps)
        gate = jax.nn.silu(
            qmm(h, lp["w_gate"], pok, pok4).astype(jnp.float32))
        up = qmm(h, lp["w_up"], pok, pok4).astype(jnp.float32)
        x = x + qmm((gate * up).astype(x.dtype), lp["w_down"], pok, pok4)
        return (x, ck_all, cv_all, ks_all, vs_all, li + 1), None

    (x, new_k, new_v, new_ks, new_vs, _), _ = jax.lax.scan(
        layer, (x, cache.k, cache.v, cache.k_scale, cache.v_scale,
                jnp.int32(0)), params["layers"])
    x = rms_norm(x, params["final_norm"], cfg.rms_eps)
    # The T=1 int8 kernels gate themselves on shape inside qmm/
    # matmul_tied (x.shape[1] == 1), so the verify block transparently
    # takes the XLA dequant path for its head matmul.
    if cfg.tie_embeddings:
        logits = matmul_tied(x, params["embed"],
                             pallas_int8).astype(jnp.float32)
    else:
        logits = qmm(x, params["lm_head"], pallas_int8).astype(jnp.float32)
    return logits, KVCache(k=new_k, v=new_v, k_scale=new_ks,
                           v_scale=new_vs)


def forward_decode(params: Params, cfg: ModelConfig, cur: jnp.ndarray,
                   positions: jnp.ndarray, cache: KVCache,
                   write_mask: jnp.ndarray, *, attn_len: int,
                   pallas_int8: bool = False, pallas_int4: bool = False,
                   block_table: jnp.ndarray | None = None,
                   block_size: int = 0, pallas_paged: bool = False,
                   pallas_dense: bool = False,
                   ) -> tuple[jnp.ndarray, KVCache]:
    """One decode step [B] -> logits [B, V], cache updated IN PLACE.

    The throughput-critical specialisation of ``forward`` for T=1 — see
    ``forward_decode_multi`` for the mechanics (including the paged-
    tier ``block_table`` routing). (``forward``'s layer scan threads
    the cache as scan xs/ys, and XLA materialises the stacked ys every
    call — a full read+write of the attention region per step, ~1.1
    GB/step at a 512 bucket for the 1B model; the scatter form traced
    at 3.96 vs 4.99 ms/step on v5e-1.)
    """
    logits, new_cache = forward_decode_multi(
        params, cfg, cur[:, None], positions, cache, write_mask,
        attn_len=attn_len, pallas_int8=pallas_int8,
        pallas_int4=pallas_int4,
        block_table=block_table, block_size=block_size,
        pallas_paged=pallas_paged, pallas_dense=pallas_dense)
    return logits[:, 0], new_cache


def param_count(params: Params) -> int:
    return sum(int(np.prod(p.shape)) for p in jax.tree.leaves(params))
