"""Monitoring endpoints on a dedicated port.

Capability parity with the reference's Flask sidecar
(app/monitoring/service_monitor.py:85-137: /health with psutil system
stats and threshold warnings, k8s-style /health/ready and /health/live,
/metrics, /info), rebuilt as a second aiohttp app in the same event loop
(no extra thread, no Flask) and backed by the ONE process-wide metrics
registry — fixing the reference gap where the sidecar's counters were
never wired and /metrics always reported zeros (SURVEY.md §5).

/metrics serves Prometheus text; /metrics.json serves the JSON form.
"""

from __future__ import annotations

import psutil
from aiohttp import web

from fasttalk_tpu import __version__
from fasttalk_tpu.utils.metrics import get_metrics


def build_monitoring_app(ready_check=None) -> web.Application:
    app = web.Application()

    async def health(request: web.Request) -> web.Response:
        cpu = psutil.cpu_percent(interval=0)
        mem = psutil.virtual_memory()
        m = get_metrics()
        body = {
            "status": "healthy",
            "uptime_seconds": m.uptime(),
            "system": {
                "cpu_percent": cpu,
                "memory_percent": mem.percent,
                "memory_available_gb": mem.available / (1024 ** 3),
            },
            "metrics": m.to_dict(),
        }
        warnings = []
        if cpu > 90:
            warnings.append("High CPU usage")
        if mem.percent > 90:
            warnings.append("High memory usage")
        if warnings:
            body["warnings"] = warnings
        return web.json_response(body)

    async def ready(request: web.Request) -> web.Response:
        if ready_check is not None and not ready_check():
            return web.json_response({"status": "not_ready"}, status=503)
        return web.json_response({"status": "ready"})

    async def live(request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def metrics(request: web.Request) -> web.Response:
        return web.Response(text=get_metrics().prometheus(),
                            content_type="text/plain")

    async def metrics_json(request: web.Request) -> web.Response:
        return web.json_response(get_metrics().to_dict())

    async def info(request: web.Request) -> web.Response:
        return web.json_response({
            "service": "fasttalk-tpu",
            "version": __version__,
            "uptime_seconds": get_metrics().uptime(),
        })

    app.router.add_get("/health", health)
    app.router.add_get("/health/ready", ready)
    app.router.add_get("/health/live", live)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/metrics.json", metrics_json)
    app.router.add_get("/info", info)
    return app
