"""Monitoring endpoints on a dedicated port.

Capability parity with the reference's Flask sidecar
(app/monitoring/service_monitor.py:85-137: /health with psutil system
stats and threshold warnings, k8s-style /health/ready and /health/live,
/metrics, /info), rebuilt as a second aiohttp app in the same event loop
(no extra thread, no Flask) and backed by the ONE process-wide metrics
registry — fixing the reference gap where the sidecar's counters were
never wired and /metrics always reported zeros (SURVEY.md §5).

/metrics serves Prometheus text; /metrics.json serves the JSON form.

Tracing (SURVEY.md §5 "TPU equivalent: jax.profiler trace endpoint"):
POST /profiler/start {"log_dir": ...} and POST /profiler/stop capture an
XLA device trace viewable in TensorBoard/Perfetto; GET /profiler/memory
reports live per-device HBM stats. The reference had no profiler at all
— only wall-clock log lines (logger.py:208-224, never called).
"""

from __future__ import annotations

import os
import time

import psutil
from aiohttp import web

from fasttalk_tpu import __version__
from fasttalk_tpu.observability.events import get_events
from fasttalk_tpu.observability.export import chrome_trace, jsonl_dump
from fasttalk_tpu.observability.flight import get_flight
from fasttalk_tpu.observability.perf import get_perf
from fasttalk_tpu.observability.profiler import get_profiler
from fasttalk_tpu.observability.slo import get_slo
from fasttalk_tpu.observability.trace import get_tracer
from fasttalk_tpu.observability.watchdog import get_watchdog
from fasttalk_tpu.resilience import failpoints
from fasttalk_tpu.utils.metrics import get_metrics

_profiler_state = {"active": False, "log_dir": None, "started_at": None}


def _device_memory() -> list[dict]:
    import jax

    out = []
    for d in jax.local_devices():
        stats = {}
        try:
            stats = d.memory_stats() or {}
        except Exception:
            pass
        out.append({
            "device": str(d),
            "platform": d.platform,
            "bytes_in_use": stats.get("bytes_in_use"),
            "bytes_limit": stats.get("bytes_limit"),
            "peak_bytes_in_use": stats.get("peak_bytes_in_use"),
        })
    return out


def build_monitoring_app(ready_check=None, sched_info=None,
                         supervisor_info=None, fault_http=False,
                         trace_lookup=None) -> web.Application:
    """``sched_info``: optional zero-arg callable returning the
    engine's scheduler view ({"stats": ..., "queued": [...]}, see
    engine.scheduler_debug) — surfaces the admission-control overload
    state on /health and queued position/deadline on /debug/requests
    (docs/SCHEDULING.md).

    ``supervisor_info``: optional zero-arg callable returning the
    launcher's restart-budget state; an "exhausted" supervisor marks
    /health dead (the engine will not be resurrected again —
    docs/RESILIENCE.md).

    ``fault_http``: enables POST /debug/fault (runtime fault-injection
    control, resilience/failpoints.py). OFF by default — the
    monitoring port is unauthenticated, so the mutation endpoint must
    be an explicit opt-in (FAULT_HTTP=true) and never enabled in
    production. GET /debug/fault (read-only view) is always served.

    ``trace_lookup``: optional one-arg callable (request_id → stitched
    cross-replica trace dict or None; the FleetRouter's
    ``stitched_trace``). GET /traces/{request_id} falls back to it
    when the local ring misses — on a router-fronted deployment the
    request ran on a REPLICA, so the router process's own ring never
    saw it and the old behavior was an unconditional 404
    (docs/OBSERVABILITY.md "Fleet tracing")."""
    app = web.Application()

    def _sched_view() -> dict | None:
        if sched_info is None:
            return None
        try:
            return sched_info()
        except Exception:
            return None

    async def health(request: web.Request) -> web.Response:
        cpu = psutil.cpu_percent(interval=0)
        mem = psutil.virtual_memory()
        m = get_metrics()
        body = {
            "status": "healthy",
            "uptime_seconds": m.uptime(),
            "system": {
                "cpu_percent": cpu,
                "memory_percent": mem.percent,
                "memory_available_gb": mem.available / (1024 ** 3),
            },
            "metrics": m.to_dict(),
        }
        warnings = []
        if cpu > 90:
            warnings.append("High CPU usage")
        if mem.percent > 90:
            warnings.append("High memory usage")
        sched = _sched_view()
        if sched is not None:
            body["scheduler"] = sched.get("stats")
            state = (sched.get("stats") or {}).get("state")
            if state and state != "healthy":
                body["status"] = state
                warnings.append(f"Admission control {state}")
        # Stall watchdog (observability/watchdog.py): a hung engine
        # step or token-stalled requests degrade the health surface —
        # the exact signal the reference's sidecar could never raise.
        wd = get_watchdog().status()
        body["watchdog"] = wd
        if not wd["ok"]:
            body["status"] = "degraded"
            if wd["step_stalled"]:
                warnings.append(
                    f"Engine step loop stalled "
                    f"(heartbeat {wd['heartbeat_age_s']}s old)")
            for rid in wd["token_stalled"]:
                warnings.append(f"Request {rid} token-stalled")
        # SLO burn state (observability/slo.py): a page-level burn is a
        # broken latency promise — degraded even though requests are
        # still completing.
        slo = get_slo().alert_summary()
        if slo:
            body["slo"] = slo
            for cls, state in slo.items():
                if state == "page":
                    body["status"] = "degraded"
                if state != "ok":
                    warnings.append(f"SLO burn {state} for {cls}")
        # Supervisor restart budget (docs/RESILIENCE.md): exhausted
        # means the engine is down AND will not be resurrected — the
        # strongest possible health signal.
        if supervisor_info is not None:
            try:
                sup = supervisor_info()
            except Exception:
                sup = None
            if sup is not None:
                body["supervisor"] = sup
                if sup.get("state") == "exhausted":
                    body["status"] = "dead"
                    warnings.append(
                        "Supervisor restart budget exhausted; engine "
                        "will not be restarted (restart the process)")
        # Fault injection active is always worth a warning: an
        # incident responder must see at a glance whether the incident
        # is an injected drill.
        if failpoints.enabled:
            body["fault_injection"] = {
                "active_points": failpoints.active_points()}
            warnings.append("Fault injection ACTIVE "
                            "(see GET /debug/fault)")
        if warnings:
            body["warnings"] = warnings
        return web.json_response(body)

    async def ready(request: web.Request) -> web.Response:
        if ready_check is not None and not ready_check():
            return web.json_response({"status": "not_ready"}, status=503)
        return web.json_response({"status": "ready"})

    async def live(request: web.Request) -> web.Response:
        return web.json_response({"status": "live"})

    async def metrics(request: web.Request) -> web.Response:
        # Cheap scrape-time samples: refresh the engine-step heartbeat
        # age gauge (one getattr + one float subtraction) and the
        # perf_* attribution gauges (one pass over the bounded step
        # ring) so stalls and wall-time decomposition are visible to
        # Prometheus without any background sampler.
        get_watchdog().sample()
        get_perf().sample()
        return web.Response(text=get_metrics().prometheus(),
                            content_type="text/plain")

    async def metrics_json(request: web.Request) -> web.Response:
        return web.json_response(get_metrics().to_dict())

    async def info(request: web.Request) -> web.Response:
        return web.json_response({
            "service": "fasttalk-tpu",
            "version": __version__,
            "uptime_seconds": get_metrics().uptime(),
        })

    async def profiler_start(request: web.Request) -> web.Response:
        import jax

        body = {}
        if request.can_read_body:
            try:
                body = await request.json()
            except Exception:
                pass
        # The monitoring port is unauthenticated: never let the request
        # choose an arbitrary filesystem path. Traces go under a fixed
        # base; the body may only name a subdirectory within it.
        base = os.path.realpath(
            os.environ.get("PROFILER_TRACE_DIR", "/tmp/fasttalk-tpu-trace"))
        sub = str(body.get("log_dir", ""))
        if os.path.isabs(sub):
            return web.json_response(
                {"error": "log_dir must be a relative subdirectory of "
                 f"{base} (set PROFILER_TRACE_DIR to move the base)"},
                status=400)
        log_dir = os.path.realpath(os.path.join(base, sub)) if sub else base
        if log_dir != base and not log_dir.startswith(base + os.sep):
            return web.json_response(
                {"error": "log_dir must be a relative subdirectory of "
                 f"{base}"}, status=400)
        # Check-and-claim atomically: no await between the active check
        # and the claim (body parsing above already suspended), so two
        # concurrent POSTs can't both pass the check — the loser would
        # otherwise reset active=False in its error path and orphan the
        # winner's still-running trace.
        if _profiler_state["active"]:
            return web.json_response(
                {"error": "trace already active",
                 "log_dir": _profiler_state["log_dir"]}, status=409)
        _profiler_state.update(active=True, log_dir=log_dir,
                               started_at=time.monotonic())
        try:
            # Off the event loop: profiler setup does filesystem work and
            # this loop is also serving every WebSocket token stream.
            import asyncio
            await asyncio.get_running_loop().run_in_executor(
                None, jax.profiler.start_trace, log_dir)
        except Exception as e:
            _profiler_state.update(active=False, log_dir=None,
                                   started_at=None)
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({"status": "tracing", "log_dir": log_dir})

    async def profiler_stop(request: web.Request) -> web.Response:
        import jax

        if not _profiler_state["active"]:
            return web.json_response({"error": "no active trace"}, status=409)
        duration = time.monotonic() - (_profiler_state["started_at"] or 0)
        log_dir = _profiler_state["log_dir"]
        # Release the claim before the awaited stop: a concurrent stop
        # gets a clean 409 instead of double-calling stop_trace.
        _profiler_state.update(active=False, log_dir=None, started_at=None)
        try:
            # stop_trace serializes the whole trace to disk — keep that
            # multi-second write off the serving event loop.
            import asyncio
            await asyncio.get_running_loop().run_in_executor(
                None, jax.profiler.stop_trace)
        except Exception as e:
            return web.json_response({"error": str(e)}, status=500)
        return web.json_response({"status": "stopped", "log_dir": log_dir,
                                  "duration_seconds": duration})

    async def profiler_memory(request: web.Request) -> web.Response:
        return web.json_response({"devices": _device_memory()})

    # ---- request-lifecycle tracing (observability/trace.py) ----

    async def _render_off_loop(build) -> str:
        """Build + JSON-encode an export on a worker thread: a full
        ring is hundreds of thousands of event dicts, and this app
        shares the event loop with every WebSocket token stream — a
        debug curl must not stall them. The inputs are snapshot lists
        (tracer.completed/steps copy under the tracer lock), so
        off-loop access is safe."""
        import asyncio
        import json as _json

        return await asyncio.get_running_loop().run_in_executor(
            None, lambda: _json.dumps(build()))

    async def debug_requests(request: web.Request) -> web.Response:
        """In-flight requests with current phase and age; queued ones
        additionally show their admission position, priority and
        remaining deadline (scheduler view)."""
        tracer = get_tracer()
        body = {
            "enabled": tracer.enabled,
            "requests": tracer.inflight_summary(),
        }
        sched = _sched_view()
        if sched is not None:
            body["scheduler"] = sched.get("stats")
            queued = {q["request_id"]: q
                      for q in sched.get("queued", [])}
            for r in body["requests"]:
                extra = queued.pop(r["request_id"], None)
                if extra is not None:
                    r.update(queue_position=extra["position"],
                             priority=extra["priority"],
                             deadline_in_s=extra["deadline_in_s"])
            # Entries the tracer doesn't know (tracing disabled, or a
            # trace evicted) still show up as queued work.
            body["queued_untraced"] = list(queued.values())
            # Sessions parked in the host-KV pool (docs/KVCACHE.md):
            # not live requests, but state an operator debugging "why
            # did this follow-up turn TTFT spike" needs next to the
            # queue — was the session restorable or re-prefilled?
            if sched.get("parked_sessions") is not None:
                body["kv_host"] = sched.get("kv_host")
                body["parked_sessions"] = sched["parked_sessions"]
        return web.json_response(body)

    async def traces_index(request: web.Request) -> web.Response:
        """Completed-trace ring: index by default; ?format=chrome for a
        Perfetto-loadable Chrome trace of the whole ring (+ engine-step
        telemetry row); ?format=jsonl for the offline-analysis dump
        scripts/trace_report.py consumes."""
        tracer = get_tracer()
        fmt = request.query.get("format", "")
        completed = tracer.completed()
        if fmt == "chrome":
            text = await _render_off_loop(
                lambda: chrome_trace(tracer, completed, tracer.steps()))
            return web.Response(text=text,
                                content_type="application/json")
        if fmt == "jsonl":
            import asyncio
            text = await asyncio.get_running_loop().run_in_executor(
                None, jsonl_dump, tracer, completed, tracer.steps())
            return web.Response(text=text,
                                content_type="application/x-ndjson")
        if fmt:
            return web.json_response(
                {"error": f"unknown format {fmt!r} "
                 "(expected chrome or jsonl)"}, status=400)
        return web.json_response({
            "enabled": tracer.enabled,
            "completed": [t.request_id for t in completed],
            "inflight": [t["request_id"]
                         for t in tracer.inflight_summary()],
            "engine_steps": len(tracer.steps()),
        })

    async def trace_one(request: web.Request) -> web.Response:
        """One request's trace (in-flight or completed): Chrome trace
        JSON by default, ?format=jsonl for the flat span records."""
        rid = request.match_info["request_id"]
        tracer = get_tracer()
        trace = tracer.get(rid)
        if trace is None:
            if trace_lookup is not None:
                # Router-fronted lookup fan-out: the request ran on a
                # replica, not in this process. Off-loop — the lookup
                # does HTTP fetches to every remote replica.
                import asyncio
                import json as _json

                try:
                    stitched = await asyncio.get_running_loop() \
                        .run_in_executor(None, trace_lookup, rid)
                except Exception as e:
                    return web.json_response(
                        {"error": f"fleet trace lookup failed: {e}"},
                        status=502)
                if stitched is not None and stitched.get("fragments"):
                    return web.json_response(
                        stitched,
                        dumps=lambda o: _json.dumps(o, default=str))
            return web.json_response(
                {"error": f"unknown request_id {rid!r}"}, status=404)
        if request.query.get("format") == "jsonl":
            import asyncio
            text = await asyncio.get_running_loop().run_in_executor(
                None, jsonl_dump, tracer, [trace])
            return web.Response(text=text,
                                content_type="application/x-ndjson")
        text = await _render_off_loop(
            lambda: chrome_trace(tracer, [trace]))
        return web.Response(text=text, content_type="application/json")

    # ---- perf attribution + flight recorder (ISSUE 6) ----

    async def perf(request: web.Request) -> web.Response:
        """Performance attribution report: wall-time decomposition
        (device busy / host gap / idle), padding waste, occupancy,
        useful-token throughput, MFU vs the device roofline, and the
        compile ledger (observability/perf.py)."""
        return web.json_response(get_perf().report())

    async def debug_profile(request: web.Request) -> web.Response:
        """Continuous host profiler (observability/profiler.py):
        flamegraph-collapsed text by default (pipe straight into
        flamegraph.pl / speedscope), ?format=json for the structured
        report (per-role hot stacks, engine-thread cause timeline, GC
        pauses, sampler health)."""
        prof = get_profiler()
        if request.query.get("format") == "json":
            return web.json_response(prof.report())
        if not prof.enabled:
            return web.Response(
                text="# continuous profiler disabled "
                     "(PROF_ENABLED=false)\n",
                content_type="text/plain", status=200)
        # Rendering walks the whole aggregated stack table — keep it
        # off the event loop like the trace exports above.
        import asyncio
        text = await asyncio.get_running_loop().run_in_executor(
            None, prof.collapsed)
        return web.Response(text=text, content_type="text/plain")

    async def debug_bundle(request: web.Request) -> web.Response:
        """Manually capture a flight-recorder debug bundle (same
        contents as the automatic incident captures; bypasses the rate
        limit but not the one-writer-at-a-time guard)."""
        flight = get_flight()
        if not flight.enabled:
            return web.json_response(
                {"error": "flight recorder disabled "
                 "(FLIGHT_ENABLED=0)"}, status=409)
        path = flight.trigger("manual", force=True)
        if path is None:
            return web.json_response(
                {"error": "a bundle write is already in progress",
                 **flight.stats()}, status=429)
        return web.json_response({**flight.stats(),
                                  "status": "writing", "dir": path})

    # ---- fault injection (resilience/failpoints.py, ISSUE 10) ----

    async def fault_get(request: web.Request) -> web.Response:
        """Read-only view: active rules with hit/fire counts + the
        full failpoint catalog."""
        return web.json_response(failpoints.describe())

    async def fault_post(request: web.Request) -> web.Response:
        """Arm a fault-injection spec at runtime (replaces the active
        set), or clear it: {"spec": "..."} | {"clear": true}. Gated by
        FAULT_HTTP — the monitoring port is unauthenticated and this
        endpoint injects faults on purpose."""
        if not fault_http:
            return web.json_response(
                {"error": "fault-injection HTTP control is disabled "
                 "(set FAULT_HTTP=true; never in production)"},
                status=403)
        try:
            body = await request.json()
        except Exception:
            return web.json_response(
                {"error": "body must be JSON: {\"spec\": \"...\"} or "
                 "{\"clear\": true}"}, status=400)
        if body.get("clear"):
            failpoints.clear()
            return web.json_response(failpoints.describe())
        spec = body.get("spec")
        if not isinstance(spec, str) or not spec.strip():
            return web.json_response(
                {"error": "missing \"spec\" (failpoint spec string) "
                 "or \"clear\": true"}, status=400)
        try:
            failpoints.activate(spec)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=400)
        return web.json_response(failpoints.describe())

    # ---- SLO engine + structured event log (ISSUE 3) ----

    async def slo(request: web.Request) -> web.Response:
        """Per-class SLO report: objectives, multi-window burn rates,
        alert state (ok/warn/page) and goodput
        (observability/slo.py)."""
        return web.json_response(get_slo().snapshot())

    async def events(request: web.Request) -> web.Response:
        """Newest-first structured events (?limit=N, ?kind=...,
        ?min_severity=warning|critical)."""
        try:
            limit = int(request.query.get("limit", "100"))
        except ValueError:
            return web.json_response(
                {"error": "limit must be an integer"}, status=400)
        log_ = get_events()
        return web.json_response({
            "events": log_.recent(
                limit=limit,
                kind=request.query.get("kind") or None,
                min_severity=request.query.get("min_severity") or None),
            "total_emitted": log_.total_emitted,
            "ring_size": log_.ring_size,
        })

    app.router.add_get("/health", health)
    app.router.add_get("/health/ready", ready)
    app.router.add_get("/health/live", live)
    app.router.add_get("/slo", slo)
    app.router.add_get("/perf", perf)
    app.router.add_post("/debug/bundle", debug_bundle)
    app.router.add_get("/debug/profile", debug_profile)
    app.router.add_get("/debug/fault", fault_get)
    app.router.add_post("/debug/fault", fault_post)
    app.router.add_get("/events", events)
    app.router.add_get("/metrics", metrics)
    app.router.add_get("/metrics.json", metrics_json)
    app.router.add_get("/info", info)
    app.router.add_post("/profiler/start", profiler_start)
    app.router.add_post("/profiler/stop", profiler_stop)
    app.router.add_get("/profiler/memory", profiler_memory)
    app.router.add_get("/debug/requests", debug_requests)
    app.router.add_get("/traces", traces_index)
    app.router.add_get("/traces/{request_id}", trace_one)
    return app
