"""The park/restore decision: copy cost vs prefill cost.

A restore pays one host→device copy of the parked rows; the
alternative pays recomputing the same rows through the model. Both
costs are estimated from the engine's OWN measurements — host-copy
bandwidth from the offload thread's device→host fetches, prefill
throughput from completed prefills — so the decision tracks the actual
hardware (a relayed dev attach and a real v5e differ by orders of
magnitude) instead of a hardcoded constant. Cold start is deliberately
restore-friendly: until the first prefill is measured, any matched
prefix above the floor restores (restoring is also what *produces* the
first copy measurement).

Falling through is always safe: the admission path continues into the
existing shared-prefix / delta-prefill machinery unchanged.
"""

from __future__ import annotations

import os
import threading
from typing import Any

# Cold-start estimates. Copy bandwidth is deliberately conservative
# (PCIe-ish, not the relay's worst case); prefill throughput is
# deliberately low so the first decisions favour restore.
_DEFAULT_COPY_BPS = 1e9
_DEFAULT_PREFILL_TPS = 500.0


def kv_env_defaults() -> dict[str, float]:
    """KV_* env knobs with their defaults — the same resolution
    utils.config.Config performs, for engines constructed directly
    (tests, bench) without a Config. Invalid values fall back silently
    here; Config's validated surface is where operators get errors."""
    def _f(name: str, default: float) -> float:
        raw = os.getenv(name, "").strip()
        try:
            return float(raw) if raw else default
        except ValueError:
            return default

    return {
        "budget_mb": _f("KV_HOST_BUDGET_MB", 0.0),
        "ttl_s": _f("KV_PARK_TTL_S", 600.0),
        "idle_s": _f("KV_PARK_IDLE_S", 30.0),
        "min_tokens": _f("KV_RESTORE_MIN_TOKENS", 32.0),
    }


class RestorePolicy:
    """EMA-backed cost model deciding restore-vs-prefill."""

    def __init__(self, min_tokens: int = 32):
        self.min_tokens = max(1, int(min_tokens))
        self._lock = threading.Lock()
        self._copy_bps = 0.0      # measured host-copy bytes/s EMA
        self._prefill_tps = 0.0   # measured prefill tokens/s EMA

    # ---------------- measurement feeds ----------------

    def note_copy(self, nbytes: int, seconds: float) -> None:
        """One completed device↔host KV copy (offload thread)."""
        if seconds <= 0 or nbytes <= 0:
            return
        bps = nbytes / seconds
        with self._lock:
            self._copy_bps = bps if self._copy_bps == 0.0 \
                else 0.8 * self._copy_bps + 0.2 * bps

    def note_prefill(self, tokens: int, seconds: float) -> None:
        """One completed prefill (engine thread, at activation)."""
        if seconds <= 0 or tokens <= 0:
            return
        tps = tokens / seconds
        with self._lock:
            self._prefill_tps = tps if self._prefill_tps == 0.0 \
                else 0.8 * self._prefill_tps + 0.2 * tps

    # ---------------- decisions ----------------

    def _costs(self, match_tokens: int, nbytes: int) -> tuple[float, float]:
        with self._lock:
            bps = self._copy_bps or _DEFAULT_COPY_BPS
            tps = self._prefill_tps or _DEFAULT_PREFILL_TPS
        return nbytes / bps, match_tokens / tps

    def should_restore(self, match_tokens: int, nbytes: int) -> bool:
        """Restore when the estimated copy beats recomputing the
        matched prefix. Below the token floor the fixed dispatch cost
        dominates either estimate — fall through to prefill (where the
        shared-prefix copy may still serve the rows for free)."""
        if match_tokens < self.min_tokens:
            return False
        copy_s, prefill_s = self._costs(match_tokens, nbytes)
        return copy_s < prefill_s

    def restore_saving_s(self, match_tokens: int, nbytes: int) -> float:
        """Expected seconds saved by restoring instead of prefilling
        the matched prefix (0 when restore would not be chosen) — the
        scheduler subtracts this from its queue-wait estimate at
        admission (scheduling/scheduler.py submit)."""
        if match_tokens < self.min_tokens:
            return 0.0
        copy_s, prefill_s = self._costs(match_tokens, nbytes)
        return max(0.0, prefill_s - copy_s)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "min_tokens": self.min_tokens,
                "copy_bytes_per_s": round(self._copy_bps, 1),
                "prefill_tokens_per_s": round(self._prefill_tps, 1),
            }
