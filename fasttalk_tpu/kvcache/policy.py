"""The park/restore decision: copy cost vs prefill cost.

A restore pays one host→device copy of the parked rows; the
alternative pays recomputing the same rows through the model; the
fleet fabric (docs/ROUTER.md) adds a third option — pull the parked
entry over the network from another replica's pool, paying transfer
plus copy (``decide`` prices all three). Costs are estimated from the
engine's OWN measurements — host-copy
bandwidth from the offload thread's device→host fetches, prefill
throughput from completed prefills — so the decision tracks the actual
hardware (a relayed dev attach and a real v5e differ by orders of
magnitude) instead of a hardcoded constant. Cold start is deliberately
restore-friendly: until the first prefill is measured, any matched
prefix above the floor restores (restoring is also what *produces* the
first copy measurement).

Falling through is always safe: the admission path continues into the
existing shared-prefix / delta-prefill machinery unchanged.
"""

from __future__ import annotations

import os
import threading
from typing import Any

# Cold-start estimates. Copy bandwidth is deliberately conservative
# (PCIe-ish, not the relay's worst case); prefill throughput is
# deliberately low so the first decisions favour restore.
_DEFAULT_COPY_BPS = 1e9
_DEFAULT_PREFILL_TPS = 500.0
# Cross-replica migration cold start: NIC-ish, well under the local
# copy bandwidth — deliberately still fast enough that a long parked
# session's first failover migrates (migrating is also what produces
# the first bandwidth measurement, mirroring the restore cold start).
_DEFAULT_MIGRATE_BPS = 2e8


def kv_env_defaults() -> dict[str, float]:
    """KV_* env knobs with their defaults — the same resolution
    utils.config.Config performs, for engines constructed directly
    (tests, bench) without a Config. Invalid values fall back silently
    here; Config's validated surface is where operators get errors."""
    def _f(name: str, default: float) -> float:
        raw = os.getenv(name, "").strip()
        try:
            return float(raw) if raw else default
        except ValueError:
            return default

    return {
        "budget_mb": _f("KV_HOST_BUDGET_MB", 0.0),
        "ttl_s": _f("KV_PARK_TTL_S", 600.0),
        "idle_s": _f("KV_PARK_IDLE_S", 30.0),
        "min_tokens": _f("KV_RESTORE_MIN_TOKENS", 32.0),
    }


class RestorePolicy:
    """EMA-backed cost model deciding restore-vs-prefill."""

    def __init__(self, min_tokens: int = 32):
        self.min_tokens = max(1, int(min_tokens))
        self._lock = threading.Lock()
        self._copy_bps = 0.0      # measured host-copy bytes/s EMA
        self._prefill_tps = 0.0   # measured prefill tokens/s EMA
        self._migrate_bps = 0.0   # measured replica-to-replica bytes/s

    # ---------------- measurement feeds ----------------

    def note_copy(self, nbytes: int, seconds: float) -> None:
        """One completed device↔host KV copy (offload thread)."""
        if seconds <= 0 or nbytes <= 0:
            return
        bps = nbytes / seconds
        with self._lock:
            self._copy_bps = bps if self._copy_bps == 0.0 \
                else 0.8 * self._copy_bps + 0.2 * bps

    def note_prefill(self, tokens: int, seconds: float) -> None:
        """One completed prefill (engine thread, at activation)."""
        if seconds <= 0 or tokens <= 0:
            return
        tps = tokens / seconds
        with self._lock:
            self._prefill_tps = tps if self._prefill_tps == 0.0 \
                else 0.8 * self._prefill_tps + 0.2 * tps

    def note_migrate(self, nbytes: int, seconds: float) -> None:
        """One completed cross-replica migration transfer (router's
        migrate worker): export + wire + import, end to end."""
        if seconds <= 0 or nbytes <= 0:
            return
        bps = nbytes / seconds
        with self._lock:
            self._migrate_bps = bps if self._migrate_bps == 0.0 \
                else 0.8 * self._migrate_bps + 0.2 * bps

    # ---------------- decisions ----------------

    def _costs(self, match_tokens: int, nbytes: int) -> tuple[float, float]:
        with self._lock:
            bps = self._copy_bps or _DEFAULT_COPY_BPS
            tps = self._prefill_tps or _DEFAULT_PREFILL_TPS
        return nbytes / bps, match_tokens / tps

    def should_restore(self, match_tokens: int, nbytes: int) -> bool:
        """Restore when the estimated copy beats recomputing the
        matched prefix. Below the token floor the fixed dispatch cost
        dominates either estimate — fall through to prefill (where the
        shared-prefix copy may still serve the rows for free)."""
        if match_tokens < self.min_tokens:
            return False
        copy_s, prefill_s = self._costs(match_tokens, nbytes)
        return copy_s < prefill_s

    def decide(self, match_tokens: int, nbytes: int, *,
               local: bool = True, migratable: bool = False) -> str:
        """The three-way decision the fleet fabric prices: restore the
        entry from THIS replica's host pool ("restore"), pull it over
        the network from another replica's pool then restore it
        ("migrate"), or recompute the matched prefix ("prefill").
        ``local``/``migratable`` gate which options exist — the router
        calls with local=False (the entry is on the dying/draining
        replica, not the target); the engine's own admission path is
        the local=True, migratable=False case should_restore covers.
        Migration pays the transfer AND the target's host→device copy;
        below the token floor the fixed dispatch cost dominates every
        estimate and prefill wins outright."""
        if match_tokens < self.min_tokens:
            return "prefill"
        with self._lock:
            bps = self._copy_bps or _DEFAULT_COPY_BPS
            tps = self._prefill_tps or _DEFAULT_PREFILL_TPS
            mbps = self._migrate_bps or _DEFAULT_MIGRATE_BPS
        restore_s = nbytes / bps
        options = {"prefill": match_tokens / tps}
        if local:
            options["restore"] = restore_s
        if migratable:
            options["migrate"] = nbytes / mbps + restore_s
        return min(options, key=options.get)

    def restore_saving_s(self, match_tokens: int, nbytes: int) -> float:
        """Expected seconds saved by restoring instead of prefilling
        the matched prefix (0 when restore would not be chosen) — the
        scheduler subtracts this from its queue-wait estimate at
        admission (scheduling/scheduler.py submit)."""
        if match_tokens < self.min_tokens:
            return 0.0
        copy_s, prefill_s = self._costs(match_tokens, nbytes)
        return max(0.0, prefill_s - copy_s)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "min_tokens": self.min_tokens,
                "copy_bytes_per_s": round(self._copy_bps, 1),
                "prefill_tokens_per_s": round(self._prefill_tps, 1),
                "migrate_bytes_per_s": round(self._migrate_bps, 1),
            }
