"""Session KV host-offload tier (docs/KVCACHE.md).

A paging layer between the engine's fixed HBM decode slots and full
re-prefill: when a session's slot is evicted (or the session has sat
idle), its kept KV rows are snapshotted into a budgeted host-RAM pool;
when the session returns, the rows are copied back and only the token
delta is prefilled. Concurrent *sessions* stop being bounded by
*slots*, and a follow-up turn's TTFT drops from O(history prefill) to
O(host→device copy + delta prefill).

- hostpool.py — the budgeted LRU/TTL pool of parked entries
- offload.py  — the dedicated copy thread + length-bucketed jitted
  device↔host copy programs
- policy.py   — the park/restore decision (copy cost vs prefill cost)
- blocks.py   — paged-tier block allocator (KV_LAYOUT=paged)
- radix.py    — radix-tree automatic prefix cache over the block pool
"""

from fasttalk_tpu.kvcache.hostpool import (HostKVPool, ParkedKV,
                                           entry_problem, strip_device)
from fasttalk_tpu.kvcache.offload import KVOffloader
from fasttalk_tpu.kvcache.policy import RestorePolicy, kv_env_defaults
from fasttalk_tpu.kvcache.radix import RadixTree, chain_digest

__all__ = ["HostKVPool", "ParkedKV", "KVOffloader", "RestorePolicy",
           "kv_env_defaults", "entry_problem", "strip_device",
           "RadixTree", "chain_digest"]
