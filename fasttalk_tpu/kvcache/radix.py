"""Radix-tree automatic prefix cache over the paged KV block pool.

SGLang's RadixAttention / vLLM's automatic prefix caching, adapted to
this engine's paged tier (docs/KVCACHE.md): a radix tree whose nodes
own *block-size-aligned* token runs and the pool block ids holding
their KV rows. Every admission silently reuses the longest cached
block chain — no explicit prefix registration — and every finished or
parked session donates its clean prefix blocks to the tree instead of
freeing them.

Structure
---------
- Each node owns a run of whole blocks: ``tokens`` (len a multiple of
  ``block_size``) and a parallel ``blocks`` list of pool ids. Children
  are keyed by the chained digest of the child's *first* block, so
  siblings always differ in their first block (splits happen only at
  block boundaries, hence a partially-matching child is split into a
  shared-prefix parent plus the diverging remainder).
- Per-block *chain digests*: ``h_i = sha1(h_{i-1} || tokens_i)``. The
  digest after block ``i`` commits to the whole token prefix through
  block ``i``, which is what makes it usable as the fleet router's
  placement key (router/policy.py) — two prompts share a chain-digest
  prefix iff they share the underlying cached blocks.

Refcount contract (kvcache/blocks.py)
-------------------------------------
The tree owns exactly one allocator *hold* per block it references.
A slot that admits through ``match`` aliases the chain into its table
(ref goes to >= 2); the tree block becomes evictable again only when
every aliasing slot has released it (ref back to 1). Because slots
alias chain *prefixes*, refcounts are non-increasing along any chain,
so trimming a leaf from its tail while ``ref == 1`` can never free a
block a slot still reads — the chaos suite asserts exactly this
(tests/test_chaos.py).

Eviction
--------
``evict(need)`` walks leaves in policy order (``lru`` by last touch,
``fifo`` by insertion) and trims tail blocks with ``ref == 1``,
deleting emptied nodes, until ``need`` blocks returned to the free
list or nothing evictable remains. The allocator's pressure callback
(installed by the engine) calls this from inside ``_take``, so cached
prefixes are reclaimed *before* a live admission is shed.
"""

from __future__ import annotations

import hashlib

from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("kvcache.radix")

EVICT_POLICIES = ("lru", "fifo")


def chain_digest(prev: str, chunk: bytes) -> str:
    """One link of the chained prefix hash: commits to ``prev`` (the
    digest of everything before) plus this chunk. Shared by the tree
    (token blocks) and the fleet router (char blocks of leading
    messages) so placement keys and cache keys agree in shape."""
    h = hashlib.sha1()
    h.update(prev.encode("ascii"))
    h.update(chunk)
    return h.hexdigest()


def _block_bytes(tokens: list[int]) -> bytes:
    # Fixed-width little-endian token ids: unambiguous concatenation.
    return b"".join(t.to_bytes(4, "little", signed=False)
                    for t in tokens)


class _Node:
    __slots__ = ("parent", "tokens", "blocks", "digests", "children",
                 "last_access", "created")

    def __init__(self, parent: "_Node | None") -> None:
        self.parent = parent
        self.tokens: list[int] = []     # multiple of block_size
        self.blocks: list[int] = []     # pool ids, parallel per block
        self.digests: list[str] = []    # chain digest after block i
        self.children: dict[str, _Node] = {}
        self.last_access = 0
        self.created = 0


class RadixTree:
    """Prefix cache over a ``BlockAllocator``. All methods run on the
    engine thread (same no-lock discipline as the allocator); the
    monitoring port only reads ``stats()`` snapshots."""

    def __init__(self, alloc, *, min_free_blocks: int = 0,
                 evict_policy: str = "lru",
                 token_bytes: int = 0) -> None:
        if evict_policy not in EVICT_POLICIES:
            raise ValueError(
                f"unknown radix evict policy {evict_policy!r} "
                f"(expected one of {EVICT_POLICIES})")
        self.alloc = alloc
        self.block_size = alloc.block_size
        self.min_free_blocks = min_free_blocks
        self.evict_policy = evict_policy
        # Bytes of device KV per token row (all layers, K+V) — for the
        # bytes-saved counter; 0 when the engine doesn't care.
        self.token_bytes = token_bytes
        self._root = _Node(None)
        self._tick = 0
        self._blocks = 0          # blocks currently held by the tree
        self._nodes = 0
        # Cumulative counters (mirrored to Prometheus below).
        self.lookups = 0
        self.hits = 0
        self.hit_tokens = 0
        self.inserted_blocks = 0
        self.evicted_blocks = 0
        m = get_metrics()
        self._m_nodes = m.gauge(
            "kv_radix_nodes", "radix prefix-cache tree nodes")
        self._m_blocks = m.gauge(
            "kv_radix_blocks",
            "device KV blocks held by the radix prefix cache")
        self._m_hit_tokens = m.counter(
            "kv_radix_hit_tokens_total",
            "prompt tokens served from the radix prefix cache "
            "instead of prefill")
        self._m_bytes_saved = m.counter(
            "kv_radix_bytes_saved_total",
            "device KV bytes not re-computed thanks to radix "
            "prefix-cache hits")
        self._m_lookups = m.counter(
            "kv_radix_lookups_total", "radix prefix-cache lookups")
        self._m_hits = m.counter(
            "kv_radix_hits_total",
            "radix prefix-cache lookups matching >= 1 block")
        self._m_inserted = m.counter(
            "kv_radix_inserted_blocks_total",
            "blocks donated to the radix prefix cache")
        self._m_evicted = m.counter(
            "kv_radix_evicted_blocks_total",
            "radix prefix-cache blocks reclaimed under pool pressure")

    # ---------------- queries ----------------

    def nodes(self) -> int:
        return self._nodes

    def blocks(self) -> int:
        return self._blocks

    def evictable_blocks(self) -> int:
        """Held blocks no slot currently aliases (ref == 1) — what
        eviction could return to the free list right now. Admission
        counts these as available (engine ``_paged_admissible``)."""
        n = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            for blk in node.blocks:
                if self.alloc.ref(blk) == 1:
                    n += 1
            stack.extend(node.children.values())
        return n

    # ---------------- match (admission) ----------------

    def match(self, tokens: list[int], max_blocks: int | None = None,
              count: bool = True) -> tuple[list[int], str]:
        """Longest cached chain that is a block-aligned prefix of
        ``tokens``. Returns (pool block ids, chain digest at the match
        end). Touches the path for LRU. The caller aliases the blocks
        into a slot table (bumping refs) *before* anything can trigger
        eviction, and credits the hit with ``note_hit`` only once the
        alias actually lands (a peeked-then-discarded match must not
        inflate the hit counters)."""
        bs = self.block_size
        limit = len(tokens) // bs
        if max_blocks is not None:
            limit = min(limit, max_blocks)
        if count:
            self.lookups += 1
            self._m_lookups.inc(1)
        out: list[int] = []
        digest = ""
        self._tick += 1
        node = self._root
        pos = 0
        while len(out) < limit:
            key = chain_digest(
                digest, _block_bytes(tokens[pos:pos + bs]))
            child = node.children.get(key)
            if child is None:
                break
            child.last_access = self._tick
            # First block already verified via the keyed digest; the
            # rest of the run must match token-for-token.
            nb = len(child.blocks)
            take = 0
            d = digest
            for i in range(min(nb, limit - len(out))):
                lo = pos + i * bs
                if i and child.tokens[i * bs:(i + 1) * bs] \
                        != tokens[lo:lo + bs]:
                    break
                d = child.digests[i]
                take = i + 1
            out.extend(child.blocks[:take])
            pos += take * bs
            digest = d
            if take < nb:       # diverged (or hit limit) mid-node
                break
            node = child
        return out, digest

    def note_hit(self, tokens_served: int) -> None:
        """Credit a consumed match (the engine aliased the chain into
        a slot table): hit-rate, tokens and bytes-saved counters."""
        self.hits += 1
        self._m_hits.inc(1)
        self.hit_tokens += tokens_served
        self._m_hit_tokens.inc(tokens_served)
        if self.token_bytes:
            self._m_bytes_saved.inc(tokens_served * self.token_bytes)

    # ---------------- insert (retirement / park / stamp) ----------------

    def insert(self, tokens: list[int], table: list[int],
               written: int | None = None) -> int:
        """Donate a slot's clean prefix to the tree. ``tokens`` is the
        slot history, ``table`` its block table; only whole blocks
        whose rows are fully written (``written`` caps, default all of
        ``tokens``) are eligible. Blocks the tree already caches for
        this token prefix are skipped (the slot's duplicates free on
        release as usual); genuinely new suffix blocks get one
        allocator hold each. Returns blocks newly held."""
        bs = self.block_size
        n_tok = len(tokens) if written is None else min(written,
                                                       len(tokens))
        nb = min(n_tok // bs, len(table))
        if nb <= 0:
            return 0
        self._tick += 1
        node = self._root
        node.last_access = self._tick
        digest = ""
        i = 0       # blocks consumed
        while i < nb:
            key = chain_digest(
                digest, _block_bytes(tokens[i * bs:(i + 1) * bs]))
            child = node.children.get(key)
            if child is None:
                break
            child.last_access = self._tick
            cb = len(child.blocks)
            same = 0
            d = digest
            for j in range(min(cb, nb - i)):
                lo = (i + j) * bs
                if j and child.tokens[j * bs:(j + 1) * bs] \
                        != tokens[lo:lo + bs]:
                    break
                d = child.digests[j]
                same = j + 1
            i += same
            digest = d
            if same < cb:
                if i < nb:
                    # Diverged mid-node: split so the shared prefix
                    # becomes the parent of both remainders.
                    node = self._split(child, same)
                    node.last_access = self._tick
                    break
                return 0    # prefix fully cached (ends mid-node)
            node = child
        if i >= nb:
            return 0        # prefix fully cached at a node boundary
        # New suffix: one leaf owning all remaining blocks.
        leaf = _Node(node)
        leaf.tokens = list(tokens[i * bs:nb * bs])
        leaf.blocks = list(table[i:nb])
        d = digest
        for j in range(nb - i):
            d = chain_digest(
                d, _block_bytes(leaf.tokens[j * bs:(j + 1) * bs]))
            leaf.digests.append(d)
        leaf.last_access = leaf.created = self._tick
        key = chain_digest(
            digest, _block_bytes(leaf.tokens[:bs]))
        node.children[key] = leaf
        self.alloc.hold(leaf.blocks)
        took = len(leaf.blocks)
        self._nodes += 1
        self._blocks += took
        self.inserted_blocks += took
        self._m_inserted.inc(took)
        self._update_gauges()
        # Keep the configured free headroom: the cache must never be
        # the reason the next admission sheds.
        if self.min_free_blocks and \
                self.alloc.available() < self.min_free_blocks:
            self.evict(self.min_free_blocks - self.alloc.available())
        return took

    def _split(self, node: _Node, at_blocks: int) -> _Node:
        """Split ``node`` so its first ``at_blocks`` blocks become a
        new parent and the remainder stays in ``node`` (re-keyed as
        its child). Returns the new parent."""
        assert 0 < at_blocks < len(node.blocks)
        bs = self.block_size
        parent = node.parent
        head = _Node(parent)
        head.tokens = node.tokens[:at_blocks * bs]
        head.blocks = node.blocks[:at_blocks]
        head.digests = node.digests[:at_blocks]
        head.last_access = node.last_access
        head.created = node.created
        # Re-key node under its (now shorter) first block. Only the
        # root has an empty run, so the chain digest at the start of
        # node's run is the parent's last digest (or "" at the root).
        prev = parent.digests[-1] if parent.digests else ""
        old_key = chain_digest(prev, _block_bytes(node.tokens[:bs]))
        del parent.children[old_key]
        parent.children[chain_digest(prev,
                                     _block_bytes(head.tokens[:bs]))] \
            = head
        node.tokens = node.tokens[at_blocks * bs:]
        node.blocks = node.blocks[at_blocks:]
        node.digests = node.digests[at_blocks:]
        node.parent = head
        head.children[chain_digest(head.digests[-1],
                                   _block_bytes(node.tokens[:bs]))] \
            = node
        self._nodes += 1
        self._update_gauges()
        return head

    # ---------------- eviction ----------------

    def evict(self, need: int) -> int:
        """Reclaim up to ``need`` blocks from unreferenced (ref == 1)
        leaf tails, policy order. Returns blocks freed."""
        freed = 0
        while freed < need:
            leaf = self._pick_victim()
            if leaf is None:
                break
            trimmed: list[int] = []
            while leaf.blocks and len(trimmed) < need - freed \
                    and self.alloc.ref(leaf.blocks[-1]) == 1:
                trimmed.append(leaf.blocks.pop())
                leaf.digests.pop()
                del leaf.tokens[-self.block_size:]
            if not trimmed:
                break   # victim pinned by a slot alias — nothing left
            self.alloc.unhold(trimmed)
            freed += len(trimmed)
            self._blocks -= len(trimmed)
            self.evicted_blocks += len(trimmed)
            self._m_evicted.inc(len(trimmed))
            if not leaf.blocks:
                self._remove(leaf)
        if freed:
            self._update_gauges()
            log.debug(
                f"radix evicted {freed} block(s) under pool pressure")
        return freed

    def _pick_victim(self) -> _Node | None:
        """Oldest leaf (policy order) with at least one trimmable tail
        block. Leaves whose tails are slot-aliased are skipped — their
        refcount >= 2 blocks must never be evicted."""
        best: _Node | None = None
        best_key = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is self._root or node.children or not node.blocks:
                continue
            if self.alloc.ref(node.blocks[-1]) != 1:
                continue
            key = (node.last_access if self.evict_policy == "lru"
                   else node.created)
            if best is None or key < best_key:
                best, best_key = node, key
        return best

    def _remove(self, node: _Node) -> None:
        assert not node.children and not node.blocks
        parent = node.parent
        for key, child in list(parent.children.items()):
            if child is node:
                del parent.children[key]
                break
        self._nodes -= 1

    def clear(self) -> int:
        """Drop every hold and reset the tree (engine restart /
        disable). Returns blocks released."""
        released = 0
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node.blocks:
                self.alloc.unhold(node.blocks)
                released += len(node.blocks)
        self._root = _Node(None)
        self._nodes = 0
        self._blocks = 0
        self._update_gauges()
        return released

    # ---------------- observability ----------------

    def _update_gauges(self) -> None:
        self._m_nodes.set(self._nodes)
        self._m_blocks.set(self._blocks)

    def stats(self) -> dict:
        return {
            "nodes": self._nodes,
            "blocks": self._blocks,
            "evictable_blocks": self.evictable_blocks(),
            "lookups": self.lookups,
            "hits": self.hits,
            "hit_rate": (round(self.hits / self.lookups, 4)
                         if self.lookups else 0.0),
            "hit_tokens": self.hit_tokens,
            "bytes_saved": self.hit_tokens * self.token_bytes,
            "inserted_blocks": self.inserted_blocks,
            "evicted_blocks": self.evicted_blocks,
            "evict_policy": self.evict_policy,
        }

    def check_integrity(self) -> None:
        """Test surface: structural invariants — block-aligned runs,
        digest chains consistent, child keys correct, hold accounting
        matches the allocator."""
        bs = self.block_size
        seen: set[int] = set()
        nodes = 0

        def walk(node: _Node, digest: str) -> None:
            nonlocal nodes
            if node is not self._root:
                nodes += 1
                assert node.tokens and len(node.tokens) % bs == 0
                assert len(node.blocks) == len(node.tokens) // bs
                assert len(node.digests) == len(node.blocks)
                d = digest
                for j, blk in enumerate(node.blocks):
                    assert blk not in seen, f"block {blk} in tree twice"
                    seen.add(blk)
                    d = chain_digest(
                        d, _block_bytes(
                            node.tokens[j * bs:(j + 1) * bs]))
                    assert d == node.digests[j], "digest chain broken"
                digest = d
            for key, child in node.children.items():
                assert child.parent is node
                assert key == chain_digest(
                    digest, _block_bytes(child.tokens[:bs]))
                walk(child, digest)

        walk(self._root, "")
        assert nodes == self._nodes, \
            f"node count {self._nodes} != walked {nodes}"
        assert len(seen) == self._blocks, \
            f"block count {self._blocks} != walked {len(seen)}"
        for blk in seen:
            assert self.alloc.ref(blk) >= 1
