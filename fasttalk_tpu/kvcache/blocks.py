"""Host-side block allocator for the paged KV tier (KV_LAYOUT=paged).

The device holds one flat pool of KV rows per layer —
``[L, num_blocks * block_size, Kv, H]`` — and every decode slot maps its
logical token positions onto pool rows through a *block table*: entry
``i`` of a slot's table names the pool block holding that slot's
positions ``[i*block_size, (i+1)*block_size)``. This module is the pure
bookkeeping half: which blocks are free, which slot(s) reference each
block, and what each slot's table currently says. All device-side data
movement (gather reads, scatter writes, the copy-on-write block copy)
lives in the engine's jitted programs; everything here is plain Python
on the engine thread (no locks by design, same discipline as
engine/slots.py — the monitoring port reads ``stats()``, which only
touches atomically-swapped ints and copies).

Refcounts make shared prefixes *aliasing* instead of row copies: a
fresh admission whose prompt starts with blocks resident in another
slot appends the same block ids to its own table (``alias``) and bumps
their refcounts; only a partially-shared tail block ever needs a device
copy (copy-on-write, driven by the engine). A block returns to the free
list when its last referent drops it — eviction, truncation on history
divergence, or session release.

Beyond slot tables, a block may carry *holds* — references owned by a
non-slot structure (the radix prefix tree, kvcache/radix.py). A hold is
one refcount like any table entry; ``hold``/``unhold`` maintain them,
and the pressure callback installed with ``set_pressure`` lets the
holder shed refcount-free holds when ``_take`` would otherwise raise,
so cached-but-unreferenced prefix blocks are reclaimed before a live
admission is shed.

Invariant (asserted by ``check_leaks``): every block is either on the
free list with refcount 0, or its refcount equals its table
multiplicity plus its hold multiplicity. ``kv.block_alloc`` is a chaos
failpoint at the single place blocks are taken from the free list, so
pool exhaustion mid-prefill is a rehearsed incident, not a novel one
(docs/RESILIENCE.md).
"""

from __future__ import annotations

from fasttalk_tpu.resilience import failpoints as _fp
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("kvcache.blocks")


def blocks_for(tokens: int, block_size: int) -> int:
    """Blocks needed to hold ``tokens`` rows (ceil division)."""
    return -(-max(0, tokens) // block_size)


class BlockExhausted(RuntimeError):
    """The pool has no free block for a required allocation."""


class BlockAllocator:
    """Free-list + refcount bookkeeping over ``num_blocks`` fixed-size
    blocks, with one block table per decode slot."""

    def __init__(self, num_blocks: int, block_size: int,
                 num_slots: int) -> None:
        if num_blocks <= 0:
            raise ValueError("num_blocks must be > 0")
        if block_size <= 0 or block_size & (block_size - 1):
            raise ValueError(
                f"block_size must be a power of two, got {block_size}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._ref = [0] * num_blocks
        # Pop from the end → low block ids hand out first (stable ids
        # make test assertions and debug dumps readable).
        self._free = list(range(num_blocks - 1, -1, -1))
        self._tables: list[list[int]] = [[] for _ in range(num_slots)]
        self.cow_copies = 0       # copy-on-write block copies performed
        self.alias_events = 0     # alias() calls that shared >= 1 block
        m = get_metrics()
        self._m_total = m.gauge(
            "kv_blocks_total", "device KV block-pool size (blocks)")
        self._m_in_use = m.gauge(
            "kv_blocks_in_use", "device KV blocks with refcount >= 1")
        self._m_aliased = m.gauge(
            "kv_blocks_aliased",
            "device KV blocks shared by more than one slot "
            "(refcount >= 2)")
        self._m_frag = m.gauge(
            "kv_block_fragmentation",
            "fraction of in-use KV block capacity holding no live "
            "token rows (allocation granularity waste)")
        self._m_total.set(num_blocks)
        self._aliased = 0
        # Non-slot references (block id -> hold multiplicity), owned by
        # the radix prefix cache. Counted inside _ref like table
        # entries; kept separately so check_leaks can prove the split.
        self._held: dict[int, int] = {}
        # Invoked by _take when the free list cannot cover a request:
        # cb(shortfall_blocks) should release holds (via unhold) and
        # may return the number of blocks it freed. Installed by the
        # engine when the radix cache is on.
        self._pressure = None
        self._update_gauges()

    # ---------------- queries ----------------

    def available(self) -> int:
        return len(self._free)

    def in_use(self) -> int:
        return self.num_blocks - len(self._free)

    def table(self, slot: int) -> list[int]:
        """The slot's live block table (do not mutate)."""
        return self._tables[slot]

    def slot_blocks(self, slot: int) -> int:
        return len(self._tables[slot])

    def tail_shared(self, slot: int) -> bool:
        """True when the slot's last table block is referenced by
        another slot too — writing into it would corrupt the other
        referent's trusted rows (the engine copy-on-writes first)."""
        t = self._tables[slot]
        return bool(t) and self._ref[t[-1]] > 1

    def block_shared(self, slot: int, index: int) -> bool:
        return self._ref[self._tables[slot][index]] > 1

    def ref(self, blk: int) -> int:
        return self._ref[blk]

    def held(self) -> int:
        """Distinct blocks currently carrying at least one hold."""
        return len(self._held)

    # ---------------- allocation ----------------

    def _take(self, n: int) -> list[int]:
        """Pop ``n`` free blocks (all-or-nothing). The ``kv.block_alloc``
        failpoint fires BEFORE any state changes, so an injected
        exhaustion leaves the accounting exactly as it found it."""
        if n <= 0:
            return []
        if _fp.enabled:
            _fp.fire("kv.block_alloc", exc=BlockExhausted, need=str(n))
        if n > len(self._free) and self._pressure is not None:
            # Reclaim radix-held blocks before declaring exhaustion —
            # the callback unholds LRU cached prefixes, growing _free.
            self._pressure(n - len(self._free))
        if n > len(self._free):
            raise BlockExhausted(
                f"KV block pool exhausted: need {n} blocks, "
                f"{len(self._free)} free of {self.num_blocks}")
        out = [self._free.pop() for _ in range(n)]
        for b in out:
            self._ref[b] = 1
        return out

    def ensure(self, slot: int, tokens: int) -> bool:
        """Grow the slot's table to cover ``tokens`` positions.
        Returns False (state untouched) when the pool cannot supply the
        missing blocks; never shrinks (see ``truncate``)."""
        need = blocks_for(tokens, self.block_size) - len(self._tables[slot])
        if need <= 0:
            return True
        try:
            fresh = self._take(need)
        except BlockExhausted:
            return False
        self._tables[slot].extend(fresh)
        self._update_gauges()
        return True

    def append_block(self, slot: int) -> int | None:
        """Append one fresh block to the slot's table (the engine's
        copy-on-write target). None when the pool is empty."""
        try:
            blk = self._take(1)[0]
        except BlockExhausted:
            return None
        self._tables[slot].append(blk)
        self._update_gauges()
        return blk

    # ---------------- release ----------------

    def _drop(self, blk: int) -> None:
        ref = self._ref[blk]
        assert ref > 0, f"double free of KV block {blk}"
        if ref == 2:
            self._aliased -= 1
        self._ref[blk] = ref - 1
        if ref == 1:
            self._free.append(blk)

    def truncate(self, slot: int, tokens: int) -> int:
        """Drop table blocks beyond what ``tokens`` positions need
        (history divergence, post-finish hygiene). Returns blocks
        dropped."""
        keep = blocks_for(tokens, self.block_size)
        t = self._tables[slot]
        dropped = 0
        while len(t) > keep:
            self._drop(t.pop())
            dropped += 1
        if dropped:
            self._update_gauges()
        return dropped

    def release(self, slot: int) -> None:
        """Drop the slot's whole table (unpin/eviction/release)."""
        self.truncate(slot, 0)

    # ---------------- holds (radix prefix cache) ----------------

    def set_pressure(self, cb) -> None:
        """Install the reclaim-under-pressure callback (or None).
        ``cb(shortfall)`` runs inside ``_take`` when the free list is
        short, after the chaos failpoint and before the exhaustion
        raise; it should ``unhold`` cached blocks to grow the pool."""
        self._pressure = cb

    def hold(self, blocks: list[int]) -> None:
        """Take one non-slot reference on each (live) block. The
        holder keeps the rows alive after every slot table drops
        them."""
        for blk in blocks:
            ref = self._ref[blk]
            assert ref > 0, f"hold on free KV block {blk}"
            if ref == 1:
                self._aliased += 1
            self._ref[blk] = ref + 1
            self._held[blk] = self._held.get(blk, 0) + 1
        if blocks:
            self._update_gauges()

    def unhold(self, blocks: list[int]) -> None:
        """Release one hold per block; blocks whose last reference
        this was return to the free list."""
        for blk in blocks:
            h = self._held.get(blk, 0)
            assert h > 0, f"unhold without hold on KV block {blk}"
            if h == 1:
                del self._held[blk]
            else:
                self._held[blk] = h - 1
            self._drop(blk)
        if blocks:
            self._update_gauges()

    # ---------------- aliasing (shared prefix) ----------------

    def alias(self, src_slot: int, dst_slot: int, n_blocks: int) -> int:
        """Share the source slot's first ``n_blocks`` table entries
        into the (empty) destination table, bumping refcounts — the
        zero-copy shared-prefix stamp. Returns blocks aliased."""
        dst = self._tables[dst_slot]
        assert not dst, "alias target must be a fresh (empty) table"
        src = self._tables[src_slot]
        n = min(n_blocks, len(src))
        for blk in src[:n]:
            if self._ref[blk] == 1:
                self._aliased += 1
            self._ref[blk] += 1
            dst.append(blk)
        if n:
            self.alias_events += 1
            self._update_gauges()
        return n

    def alias_blocks(self, dst_slot: int, blocks: list[int]) -> int:
        """Share an explicit block chain (radix-tree match) into the
        (empty) destination table, bumping refcounts. Returns blocks
        aliased."""
        dst = self._tables[dst_slot]
        assert not dst, "alias target must be a fresh (empty) table"
        for blk in blocks:
            ref = self._ref[blk]
            assert ref > 0, f"alias of free KV block {blk}"
            if ref == 1:
                self._aliased += 1
            self._ref[blk] = ref + 1
            dst.append(blk)
        if blocks:
            self.alias_events += 1
            self._update_gauges()
        return len(blocks)

    def cow_tail(self, slot: int) -> tuple[int, int] | None:
        """Copy-on-write the slot's tail block: swap the (shared) last
        table entry for a fresh exclusive block, dropping one reference
        on the old. Returns (old_block, new_block) for the engine's
        device copy, or None when the pool is empty (the caller
        truncates to the block boundary instead)."""
        t = self._tables[slot]
        assert t, "cow_tail on an empty table"
        old = t[-1]
        try:
            new = self._take(1)[0]
        except BlockExhausted:
            return None
        t[-1] = new
        self._drop(old)
        self.cow_copies += 1
        self._update_gauges()
        return old, new

    # ---------------- observability / invariants ----------------

    def _update_gauges(self) -> None:
        self._m_in_use.set(self.in_use())
        self._m_aliased.set(self._aliased)

    def note_used_tokens(self, used_tokens: int) -> None:
        """Feed live token-row occupancy (sum of slot kept lengths over
        DISTINCT blocks' capacity) into the fragmentation gauge."""
        cap = self.in_use() * self.block_size
        frag = 1.0 - min(1.0, used_tokens / cap) if cap else 0.0
        self._m_frag.set(round(frag, 6))

    def stats(self, used_tokens: int | None = None) -> dict:
        in_use = self.in_use()
        out = {
            "total": self.num_blocks,
            "block_size": self.block_size,
            "free": len(self._free),
            "in_use": in_use,
            "aliased": self._aliased,
            "alias_events": self.alias_events,
            "cow_copies": self.cow_copies,
            "held": len(self._held),
            "tables": [len(t) for t in self._tables],
        }
        if used_tokens is not None:
            cap = in_use * self.block_size
            out["used_tokens"] = used_tokens
            out["fragmentation"] = (round(1.0 - min(1.0, used_tokens / cap),
                                          4) if cap else 0.0)
            self.note_used_tokens(used_tokens)
        return out

    def check_leaks(self) -> None:
        """Assert the pool invariant: refcounts equal table
        multiplicity plus hold multiplicity, and free+referenced
        covers every block exactly. Test/debug surface —
        O(blocks + table entries)."""
        mult: dict[int, int] = {}
        for t in self._tables:
            for blk in t:
                mult[blk] = mult.get(blk, 0) + 1
        for blk, h in self._held.items():
            assert h > 0, f"block {blk}: zero-multiplicity hold entry"
            mult[blk] = mult.get(blk, 0) + h
        free = set(self._free)
        assert len(free) == len(self._free), "free-list duplicates"
        for blk in range(self.num_blocks):
            ref = self._ref[blk]
            assert mult.get(blk, 0) == ref, \
                f"block {blk}: refcount {ref} != table+hold " \
                f"multiplicity {mult.get(blk, 0)}"
            assert (blk in free) == (ref == 0), \
                f"block {blk}: ref {ref} but free={blk in free}"
