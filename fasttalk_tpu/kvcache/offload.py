"""Async device↔host KV movement for the session offload tier.

Two halves:

- **Length-bucketed jitted copy programs.** ``make_kv_slice_fn`` reads
  one slot's leading rows out of the cache (no donation — the cache
  chain is untouched and the result is a fresh buffer the copy thread
  can fetch at leisure); ``make_kv_restore_fn`` scatters stored rows
  back into a slot (donated, so it chains with prefill/decode calls
  like every other cache op). Row lengths are power-of-two buckets
  (min 16, capped at max_len), the same discipline as the engine's
  prefill/share granules: the executable set stays at O(log max_len)
  and no unpredictable compile shape appears mid-traffic.

- **The copy thread.** Device→host fetches (``np.asarray`` of a slice
  result) block until the device catches up — that wait must never sit
  on the engine thread between decode dispatches. The engine dispatches
  the slice program (cheap, async) and hands the result to this thread,
  which fetches, builds the pool entry, and feeds the measured copy
  bandwidth back into the policy. ``prestage`` uses the same thread to
  pre-upload a parked entry's rows to the device while its follow-up
  request is still waiting in the admission queue, so the restore
  dispatch pays no host→device transfer on the admission path.
"""

from __future__ import annotations

import queue
import threading
import time
from functools import partial
from typing import Any

from fasttalk_tpu.kvcache.hostpool import HostKVPool, ParkedKV
from fasttalk_tpu.kvcache.policy import RestorePolicy
from fasttalk_tpu.resilience import failpoints as _fp
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("kvcache.offload")


def make_kv_slice_fn(cfg, bucket: int, scale_granule: int = 0):
    """Jitted read of one slot's leading ``bucket`` KV rows → fresh
    [L, bucket, Kv, H] arrays. NOT donated: the engine's cache
    reference stays live; execution is ordered before any later
    donated call by dispatch order, so the rows read are exactly the
    pre-eviction values.

    ``scale_granule`` > 0 selects the quantized tier (KV_QUANT=int8):
    the slice additionally returns the [L, bucket, G] float32 scale
    rows, so parks move int8+scales — roughly half the D2H bytes."""
    import jax

    shape = (cfg.num_layers, 1, bucket, cfg.num_kv_heads, cfg.head_dim)
    sshape = (cfg.num_layers, 1, bucket, scale_granule)

    @jax.jit
    def kv_slice(cache, slot):
        k = jax.lax.dynamic_slice(cache.k, (0, slot, 0, 0, 0), shape)
        v = jax.lax.dynamic_slice(cache.v, (0, slot, 0, 0, 0), shape)
        if scale_granule:
            ks = jax.lax.dynamic_slice(cache.k_scale, (0, slot, 0, 0),
                                       sshape)
            vs = jax.lax.dynamic_slice(cache.v_scale, (0, slot, 0, 0),
                                       sshape)
            return k[:, 0], v[:, 0], ks[:, 0], vs[:, 0]
        return k[:, 0], v[:, 0]

    return kv_slice


def make_kv_restore_fn(cfg, bucket: int, cache_cls,
                       scale_granule: int = 0):
    """Jitted write of stored rows back into a slot's leading region.
    Donates the cache so it chains in place like prefill/prefix-copy.
    Rows beyond the restored entry's trusted ``kept`` length carry
    stale values — harmless, because the caller sets ``kv_written`` to
    the matched prefix and the delta prefill overwrites from there.

    ``scale_granule`` > 0: the quantized tier restores int8 rows plus
    their [L, bucket, G] scale rows in one program — half the H2D
    bytes of a bf16 restore, which is exactly the restore-latency
    win."""
    import jax

    if scale_granule:
        @partial(jax.jit, donate_argnums=(0,))
        def kv_restore_q(cache, k_rows, v_rows, ks_rows, vs_rows, slot):
            new_k = jax.lax.dynamic_update_slice(
                cache.k, k_rows[:, None], (0, slot, 0, 0, 0))
            new_v = jax.lax.dynamic_update_slice(
                cache.v, v_rows[:, None], (0, slot, 0, 0, 0))
            new_ks = jax.lax.dynamic_update_slice(
                cache.k_scale, ks_rows[:, None], (0, slot, 0, 0))
            new_vs = jax.lax.dynamic_update_slice(
                cache.v_scale, vs_rows[:, None], (0, slot, 0, 0))
            return cache_cls(new_k, new_v, new_ks, new_vs)

        return kv_restore_q

    @partial(jax.jit, donate_argnums=(0,))
    def kv_restore(cache, k_rows, v_rows, slot):
        new_k = jax.lax.dynamic_update_slice(
            cache.k, k_rows[:, None], (0, slot, 0, 0, 0))
        new_v = jax.lax.dynamic_update_slice(
            cache.v, v_rows[:, None], (0, slot, 0, 0, 0))
        return cache_cls(new_k, new_v)

    return kv_restore


def kv_bucket(n: int, max_len: int) -> int:
    """Smallest power-of-two (min 16) covering ``n``, capped at the
    cache length — the copy executable set stays bounded at
    O(log max_len) shapes."""
    b = 16
    while b < n:
        b <<= 1
    return min(b, max_len)


def make_paged_kv_slice_fn(cfg, bucket: int, scale_granule: int = 0):
    """Paged-tier park read (KV_LAYOUT=paged): gather one slot's
    leading ``bucket`` logical rows out of the flat block pool by
    explicit pool-row indices (``read_idx`` [bucket] int32, built
    host-side from the slot's block table). NOT donated, same ordering
    contract as ``make_kv_slice_fn``. Rows whose logical position has
    no allocated block carry index 0 — they are beyond the kept length
    and the park job trims them before the entry is built, so the pool
    accounts exact per-block bytes, never dense slices."""
    import jax

    del cfg  # shapes ride the cache arrays; kept for API symmetry

    @jax.jit
    def kv_slice(cache, read_idx):
        k = cache.k[:, read_idx]
        v = cache.v[:, read_idx]
        if scale_granule:
            return (k, v, cache.k_scale[:, read_idx],
                    cache.v_scale[:, read_idx])
        return k, v

    return kv_slice


def make_paged_kv_restore_fn(cfg, bucket: int, cache_cls,
                             scale_granule: int = 0):
    """Paged-tier restore write: scatter stored rows back into freshly
    allocated pool blocks through ``write_idx`` [bucket] int32 flat
    pool rows (donated cache — chains like every other cache op).
    Entries beyond the allocated blocks carry DISTINCT out-of-range
    indices and drop, so a restore allocates exactly
    ceil(match / block_size) blocks however the stored bucket was
    padded."""
    import jax

    del cfg

    if scale_granule:
        @partial(jax.jit, donate_argnums=(0,))
        def kv_restore_q(cache, k_rows, v_rows, ks_rows, vs_rows,
                         write_idx):
            return cache_cls(
                cache.k.at[:, write_idx].set(
                    k_rows, mode="drop", unique_indices=True),
                cache.v.at[:, write_idx].set(
                    v_rows, mode="drop", unique_indices=True),
                cache.k_scale.at[:, write_idx].set(
                    ks_rows, mode="drop", unique_indices=True),
                cache.v_scale.at[:, write_idx].set(
                    vs_rows, mode="drop", unique_indices=True))

        return kv_restore_q

    @partial(jax.jit, donate_argnums=(0,))
    def kv_restore(cache, k_rows, v_rows, write_idx):
        return cache_cls(
            cache.k.at[:, write_idx].set(
                k_rows, mode="drop", unique_indices=True),
            cache.v.at[:, write_idx].set(
                v_rows, mode="drop", unique_indices=True))

    return kv_restore


def pad_rows(arr, rows: int):
    """Zero-pad a host [L, R, ...] row array to [L, rows, ...] (the
    paged tier trims parked entries to exact block bytes; restore and
    prestage pad back to the executable's power-of-two bucket)."""
    import numpy as np

    if arr.shape[1] == rows:
        return arr
    out = np.zeros((arr.shape[0], rows) + arr.shape[2:], arr.dtype)
    out[:, :arr.shape[1]] = arr
    return out


class KVOffloader:
    """Dedicated copy thread: D2H park fetches and H2D prestaging."""

    def __init__(self, pool: HostKVPool, policy: RestorePolicy,
                 tracer=None):
        self.pool = pool
        self.policy = policy
        self._tracer = tracer
        self._jobs: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        self._closed = False
        # Sessions with a park snapshot in flight: dedupes the 1 Hz
        # idle-park tick (and eviction re-parks) while the copy thread
        # lags — without this a slow D2H fetch got a duplicate slice
        # dispatch + fetch job per tick, growing the queue unboundedly
        # on exactly the slow paths the thread exists for.
        self._parking_lock = threading.Lock()
        self._parking: set[str] = set()
        m = get_metrics()
        self._m_offload = m.histogram(
            "kv_offload_ms",
            "device→host snapshot latency per parked session (dispatch "
            "to host copy landed)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000, 4000))
        self._m_restore = m.histogram(
            "kv_restore_ms",
            "host→device restore dispatch latency per admission",
            buckets=(0.5, 1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000))

    # ---------------- thread plumbing ----------------

    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._run, name="kv-offload", daemon=True)
            self._thread.start()

    def _run(self) -> None:
        while True:
            job = self._jobs.get()
            if job is None:
                return
            try:
                job()
            except Exception as e:  # the copy thread must never die
                # (FaultCrash is a BaseException and deliberately
                # escapes: the chaos suite kills this thread with it
                # and asserts the next submit() resurrects one.)
                log.error(f"kv offload job failed: {e}", exc_info=True)

    def submit(self, job) -> None:
        if self._closed:
            return
        self._ensure_thread()
        self._jobs.put(job)

    def shutdown(self) -> None:
        self._closed = True
        if self._thread is not None and self._thread.is_alive():
            self._jobs.put(None)
            self._thread.join(timeout=5)

    # ---------------- park (D2H) ----------------

    def parking(self, session_id: str) -> bool:
        """True while a park snapshot for this session is in flight."""
        with self._parking_lock:
            return session_id in self._parking

    def park(self, session_id: str, tokens: list[int], kept: int,
             bucket: int, k_rows: Any, v_rows: Any, t0: float,
             scales: tuple[Any, Any] | None = None,
             trim_rows: int | None = None) -> None:
        """Finish a park off the engine thread: fetch the slice result
        to host numpy (blocks until the device catches up — the whole
        reason this runs here), insert into the pool, feed the measured
        bandwidth to the policy, and record the ``kv_offload`` span.
        A second park for a session whose snapshot is still in flight
        is dropped (the caller re-checks parked_len on a later tick).

        ``scales``: the quantized tier's (k_scale, v_scale) slice
        results — fetched with the rows, counted in ``nbytes`` so the
        pool budget and the copy-bandwidth EMA see honest int8+scales
        bytes.

        ``trim_rows``: paged tier — keep only the leading
        ceil(kept / block_size) * block_size rows of the (power-of-two
        padded) slice before building the entry, so the pool's byte
        accounting is exact per BLOCK; ``bucket`` then records the
        padded restore shape, not the stored rows."""
        with self._parking_lock:
            if session_id in self._parking:
                return
            self._parking.add(session_id)

        def job() -> None:
            import numpy as np

            try:
                if _fp.enabled:
                    # Chaos seam: a failed/hung D2H fetch must lose
                    # only this snapshot (pool accounting untouched:
                    # the entry is never inserted), never the engine.
                    _fp.fire("kv.park.copy", session_id=session_id)
                # Bandwidth sample starts at the FETCH, not the
                # dispatch: t0 includes the slice program's queue wait
                # (and its first-use compile), which is not a cost a
                # restore pays — feeding it into the EMA made the
                # policy refuse restores that were actually 10-50x
                # cheaper than the prefill.
                tf = time.monotonic()

                def grab(arr):
                    # copy=True: on the CPU backend np.asarray of a
                    # jax array can be a zero-copy VIEW of the XLA
                    # buffer; parking that view would pin (and
                    # potentially alias back through a later
                    # device_put) device-runtime memory the pool must
                    # own outright. The paged trim composes: the
                    # compact copy IS the owned allocation.
                    host = np.asarray(arr)
                    if trim_rows is not None:
                        host = host[:, :trim_rows]
                    return np.array(host, copy=True)

                k = grab(k_rows)
                v = grab(v_rows)
                ks = vs = None
                if scales is not None:
                    ks = grab(scales[0])
                    vs = grab(scales[1])
                t1 = time.monotonic()
                nbytes = int(k.nbytes) + int(v.nbytes)
                if ks is not None:
                    nbytes += int(ks.nbytes) + int(vs.nbytes)
                entry = ParkedKV(session_id=session_id, tokens=tokens,
                                 kept=kept, bucket=bucket, k=k, v=v,
                                 k_scale=ks, v_scale=vs, nbytes=nbytes)
                if self.pool.put(entry):
                    self.policy.note_copy(entry.nbytes,
                                          max(t1 - tf, 1e-6))
                    self._m_offload.observe(max(t1 - t0, 1e-6) * 1000.0)
                    if self._tracer is not None and self._tracer.enabled:
                        # Process-level row (like engine_step): a park
                        # is not owned by any live request — it usually
                        # runs during ANOTHER session's admission.
                        self._tracer.step("kv_offload", t0, t1,
                                          session_id=session_id,
                                          tokens=kept,
                                          bytes=entry.nbytes)
            finally:
                with self._parking_lock:
                    self._parking.discard(session_id)

        self.submit(job)
        if self._closed:
            # submit dropped the job (shutdown won): release the
            # in-flight mark it would have cleared.
            with self._parking_lock:
                self._parking.discard(session_id)

    # ---------------- prestage (H2D, best-effort) ----------------

    # Prestaged (host-pool bytes duplicated into HBM awaiting their
    # restore) may hold at most this fraction of the pool budget:
    # without a cap, a burst of returning sessions could stage the
    # whole pool into HBM that is already mostly committed to weights
    # and the slot cache, and OOM the device mid-traffic.
    _PRESTAGE_FRACTION = 0.25

    def prestage(self, session_id: str) -> None:
        """Upload a parked entry's rows to the device while its
        follow-up request waits in the admission queue. Best-effort:
        a miss (no entry, the entry consumed/evicted first, or the
        staged-bytes cap reached) costs nothing — the restore falls
        back to passing numpy, paying the H2D at dispatch."""
        def job() -> None:
            import jax

            if _fp.enabled:
                # Chaos seam: prestage is best-effort by contract — a
                # failure here must cost nothing (the restore falls
                # back to passing host numpy at dispatch).
                _fp.fire("kv.prestage.copy", session_id=session_id)
            entry = self.pool.get(session_id)
            if entry is None or entry.k_dev is not None:
                return
            cap = self.pool.budget_bytes * self._PRESTAGE_FRACTION
            # The DEVICE footprint is the padded bucket, not the
            # (possibly block-trimmed) host nbytes — cap on what the
            # HBM will actually hold.
            stored = max(1, int(entry.k.shape[1]))
            staged_nbytes = entry.nbytes // stored * entry.bucket
            if self.pool.staged_bytes() + staged_nbytes > cap:
                return
            # Paged entries store exact block bytes; the restore
            # executable wants the power-of-two bucket — pad here (a
            # dense entry is already bucket rows: pad is a no-op).
            k_dev = jax.device_put(pad_rows(entry.k, entry.bucket))
            v_dev = jax.device_put(pad_rows(entry.v, entry.bucket))
            if entry.k_scale is not None:
                # Quantized tier: scales stage with their rows, and
                # BEFORE k_dev/v_dev — the restore's staged check keys
                # on those, so it can never observe rows without
                # scales.
                entry.k_scale_dev = jax.device_put(
                    pad_rows(entry.k_scale, entry.bucket))
                entry.v_scale_dev = jax.device_put(
                    pad_rows(entry.v_scale, entry.bucket))
            # Single assignment each (GIL-atomic); the consumer reads
            # k_dev/v_dev at restore time and either sees both or
            # treats the entry as unstaged.
            entry.staged_nbytes = staged_nbytes
            entry.k_dev = k_dev
            entry.v_dev = v_dev

        self.submit(job)

    def note_restore(self, seconds: float) -> None:
        self._m_restore.observe(seconds * 1000.0)
