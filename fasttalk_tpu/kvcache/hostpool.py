"""Budgeted host-RAM pool of parked session KV entries.

One entry per session: the kept-length KV rows (numpy, already fetched
off the device by the offload copy thread) plus the token ids those
rows encode. The pool is the *only* owner of parked bytes, so its
accounting is exact: entries enter through ``put`` (which enforces the
``KV_HOST_BUDGET_MB`` budget with LRU eviction), leave through
``take``/``purge``/TTL sweep, and every transition updates the
``kv_host_*`` gauges.

Thread-safety: the offload copy thread inserts, the engine thread
consumes, and the monitoring port reads — one lock serialises the few
dict ops. Entries are immutable after construction (arrays and token
lists are never mutated in place), so readers may use a popped entry
outside the lock.

Survives ``engine.restart()`` by design: the pool holds host memory
only, so a recovered engine serves follow-up turns from parked KV
instead of re-prefilling every session's history (docs/KVCACHE.md).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any

from fasttalk_tpu.observability.events import get_events
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("kvcache.hostpool")


@dataclass
class ParkedKV:
    """One session's parked KV: ``kept`` trusted rows stored in a
    power-of-two ``bucket`` (rows beyond ``kept`` are padding/stale and
    never trusted — restore sets ``kv_written`` to the matched prefix,
    exactly like the engine's watermark discipline)."""

    session_id: str
    tokens: list[int]            # kept token ids (len == kept)
    kept: int                    # trusted KV rows
    bucket: int                  # stored row length (>= kept)
    k: Any                       # np.ndarray [L, bucket, Kv, H]
    v: Any                       # np.ndarray [L, bucket, Kv, H]
    nbytes: int                  # honest host-RAM footprint (bucketed;
    #   int8 rows + scale rows under KV_QUANT=int8 — the budget and
    #   the kv_host_bytes gauge see quantized bytes, so the same
    #   KV_HOST_BUDGET_MB parks ~2x the sessions)
    parked_at: float = field(default_factory=time.monotonic)
    last_used: float = field(default_factory=time.monotonic)
    # Best-effort device-staged copies (offload.prestage): uploaded on
    # the copy thread while the request waits in the admission queue so
    # the restore dispatch pays no host→device transfer.
    k_dev: Any = None
    v_dev: Any = None
    # Device bytes the staged copies actually hold. On the paged tier
    # the host entry is TRIMMED to exact block rows but the staged
    # arrays pad back to ``bucket`` — the prestage HBM cap must count
    # the padded footprint, not the trimmed one (0 = not staged;
    # dense entries stage exactly nbytes).
    staged_nbytes: int = 0
    # Quantized tier (KV_QUANT=int8): per-row float32 scales
    # [L, bucket, G] riding alongside the int8 rows (None on the bf16
    # tier), plus their prestaged device copies.
    k_scale: Any = None
    v_scale: Any = None
    k_scale_dev: Any = None
    v_scale_dev: Any = None
    # True when the entry arrived over the fleet migration wire
    # (import_parked_kv) rather than from this replica's own park
    # path. The restore path donates IMPORTED prefixes into the radix
    # tree at admission — the decode tier builds its prefix cache from
    # handed-off prefills, not only its own traffic (router/disagg.py).
    imported: bool = False


def strip_device(entry: ParkedKV) -> ParkedKV:
    """A copy of an entry safe to hand to another replica (fleet KV
    migration, router/migrate.py): device-staged buffers (prestage
    uploads) belong to the SOURCE replica's HBM and must never travel
    with the host bytes."""
    from dataclasses import replace

    return replace(entry, k_dev=None, v_dev=None, k_scale_dev=None,
                   v_scale_dev=None, staged_nbytes=0)


def entry_problem(entry: ParkedKV) -> str | None:
    """Structural validation every migration-import path runs BEFORE
    touching a pool: a corrupted transfer must be refused with byte
    accounting intact, never inserted and trusted at restore time.
    Returns a reason string, or None when the entry is coherent."""
    import numpy as np

    if entry.kept < 1:
        return f"kept={entry.kept} (no trusted rows)"
    if len(entry.tokens) != entry.kept:
        return (f"token list length {len(entry.tokens)} != kept "
                f"{entry.kept}")
    for name in ("k", "v"):
        arr = getattr(entry, name)
        if not isinstance(arr, np.ndarray) or arr.ndim != 4:
            return f"{name} is not a [L, rows, Kv, H] array"
    if entry.k.shape != entry.v.shape:
        return f"k/v shape mismatch {entry.k.shape} vs {entry.v.shape}"
    # Every legitimate entry stores at least `kept` rows (dense parks
    # the pow2 bucket >= kept; paged trims to whole blocks >= kept) —
    # a small declared bucket must not let an under-stored entry slip
    # through to be zero-padded into "trusted" rows at import time.
    if entry.bucket < entry.kept:
        return (f"bucket {entry.bucket} cannot cover kept "
                f"{entry.kept}")
    if entry.k.shape[1] < entry.kept:
        return (f"stored rows {entry.k.shape[1]} cannot cover kept "
                f"{entry.kept}")
    if (entry.k_scale is None) != (entry.v_scale is None):
        return "one of k_scale/v_scale missing"
    if entry.k_scale is not None:
        for name in ("k_scale", "v_scale"):
            arr = getattr(entry, name)
            if not isinstance(arr, np.ndarray) or arr.ndim != 3 \
                    or arr.shape[:2] != entry.k.shape[:2]:
                return f"{name} does not match the row arrays"
    nbytes = int(entry.k.nbytes) + int(entry.v.nbytes)
    if entry.k_scale is not None:
        nbytes += int(entry.k_scale.nbytes) + int(entry.v_scale.nbytes)
    if entry.nbytes != nbytes:
        return (f"declared nbytes {entry.nbytes} != actual array "
                f"bytes {nbytes}")
    return None


class HostKVPool:
    """LRU + TTL + budget-bounded session_id → ParkedKV map."""

    def __init__(self, budget_mb: float = 0.0, ttl_s: float = 600.0,
                 clock=time.monotonic):
        self.budget_bytes = int(max(0.0, budget_mb) * 1024 * 1024)
        self.ttl_s = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._entries: dict[str, ParkedKV] = {}
        self._bytes = 0
        self._lookups = 0
        self._hits = 0
        # Instance-local counts for stats(): the registry counters are
        # process-global (shared across engine instances in one
        # process, e.g. tests), so stats() must not read them back.
        self._n_parked = 0
        self._n_restored = 0
        self._n_evicted = 0
        self._n_rejected = 0
        # Tombstones for released (dead) sessions: a park job already
        # in flight on the copy thread when the release purge ran must
        # not insert its entry afterwards — the pool would leak budget
        # to a session that can never return until TTL. Bounded; a
        # session id seen again at admission is revived (engine-seam
        # callers may reuse ids after release).
        self._dead: deque[str] = deque(maxlen=1024)
        self._dead_set: set[str] = set()
        self._events = get_events()
        m = get_metrics()
        self._m_bytes = m.gauge(
            "kv_host_bytes", "host RAM held by parked session KV")
        self._m_sessions = m.gauge(
            "kv_host_sessions", "sessions currently parked in host RAM")
        self._m_hit_ratio = m.gauge(
            "kv_restore_hit_ratio",
            "fraction of fresh-slot admissions served by a host-KV "
            "restore instead of full prefill")
        self._m_parked = m.counter(
            "kv_park_total", "session KV snapshots parked to host RAM")
        self._m_restored = m.counter(
            "kv_restore_total",
            "admissions whose kept prefix was restored from host RAM")
        self._m_evicted = m.counter(
            "kv_evicted_total",
            "parked entries evicted (budget LRU or TTL)")
        self._m_rejected = m.counter(
            "kv_park_rejected_total",
            "park attempts refused (entry alone exceeds the budget)")

    @property
    def enabled(self) -> bool:
        return self.budget_bytes > 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # ---------------- write side ----------------

    def put(self, entry: ParkedKV, *, revive: bool = False) -> bool:
        """Insert (or replace) a session's parked entry, evicting LRU
        entries while over budget. Returns False when the entry alone
        exceeds the whole budget (emits a ``kv_pressure`` event — the
        operator sized the pool below one session's history).

        ``revive=True`` re-admits a released (tombstoned) session —
        the migration import path, where the session is coming BACK.
        The tombstone is cleared only together with a successful
        insert: a refused import must leave the tombstone standing so
        a stale park snapshot still in flight cannot re-insert the
        dead session either."""
        if not self.enabled:
            return False
        with self._lock:
            if not revive and entry.session_id in self._dead_set:
                return False  # released while the copy was in flight
        if entry.nbytes > self.budget_bytes:
            self._m_rejected.inc()
            with self._lock:
                self._n_rejected += 1
            self._events.emit(
                "kv_pressure", severity="warning", coalesce_s=30.0,
                coalesce_key="oversized", reason="entry_over_budget",
                session_id=entry.session_id, entry_bytes=entry.nbytes,
                budget_bytes=self.budget_bytes)
            return False
        evicted = 0
        with self._lock:
            if revive:
                self._dead_set.discard(entry.session_id)
            old = self._entries.pop(entry.session_id, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[entry.session_id] = entry
            self._bytes += entry.nbytes
            while self._bytes > self.budget_bytes and len(self._entries) > 1:
                victim_sid = min(
                    (sid for sid in self._entries
                     if sid != entry.session_id),
                    key=lambda sid: self._entries[sid].last_used)
                self._bytes -= self._entries.pop(victim_sid).nbytes
                evicted += 1
            self._m_parked.inc()
            self._n_parked += 1
            self._update_gauges_locked()
        if evicted:
            self._m_evicted.inc(evicted)
            with self._lock:
                self._n_evicted += evicted
            self._events.emit(
                "kv_pressure", severity="warning", coalesce_s=30.0,
                coalesce_key="budget", reason="budget_eviction",
                evicted=evicted, bytes=self._bytes,
                budget_bytes=self.budget_bytes)
        return True

    def get(self, session_id: str) -> ParkedKV | None:
        """Live entry for a session (touches LRU recency); expired
        entries are dropped on access."""
        now = self._clock()
        with self._lock:
            entry = self._entries.get(session_id)
            if entry is None:
                return None
            if self.ttl_s > 0 and now - entry.last_used > self.ttl_s:
                self._entries.pop(session_id, None)
                self._bytes -= entry.nbytes
                self._m_evicted.inc()
                self._n_evicted += 1
                self._update_gauges_locked()
                return None
            entry.last_used = now
            return entry

    def take(self, session_id: str) -> ParkedKV | None:
        """Pop a session's entry (restore consumed it: the KV is about
        to be device-resident again; a later eviction re-parks it)."""
        with self._lock:
            entry = self._entries.pop(session_id, None)
            if entry is not None:
                self._bytes -= entry.nbytes
                self._update_gauges_locked()
            return entry

    def purge(self, session_id: str) -> bool:
        """Drop a session's parked entry (session released/dead — the
        pool must never leak entries for sessions that cannot return).
        Also tombstones the id so a park snapshot still in flight on
        the copy thread cannot re-insert it (see ``revive``)."""
        with self._lock:
            if session_id not in self._dead_set:
                if len(self._dead) == self._dead.maxlen:
                    self._dead_set.discard(self._dead[0])
                self._dead.append(session_id)
                self._dead_set.add(session_id)
            entry = self._entries.pop(session_id, None)
            if entry is None:
                return False
            self._bytes -= entry.nbytes
            self._update_gauges_locked()
            return True

    def revive(self, session_id: str) -> None:
        """Clear a session's released-tombstone (it was admitted
        again: engine-seam callers may reuse ids after release)."""
        with self._lock:
            self._dead_set.discard(session_id)

    def staged_bytes(self) -> int:
        """Device bytes currently held by prestage uploads awaiting
        their restore — bounds how much HBM prestaging may hold
        (kvcache/offload.py). Counts the staged (bucket-padded)
        footprint, which exceeds the trimmed host nbytes on the paged
        tier."""
        with self._lock:
            return sum(e.staged_nbytes or e.nbytes
                       for e in self._entries.values()
                       if e.k_dev is not None)

    def sweep(self, now: float | None = None) -> int:
        """TTL eviction pass (engine-loop tick); returns entries dropped."""
        if self.ttl_s <= 0:
            return 0
        now = self._clock() if now is None else now
        horizon = now - self.ttl_s
        with self._lock:
            dead = [sid for sid, e in self._entries.items()
                    if e.last_used < horizon]
            for sid in dead:
                self._bytes -= self._entries.pop(sid).nbytes
            if dead:
                self._m_evicted.inc(len(dead))
                self._n_evicted += len(dead)
                self._update_gauges_locked()
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._update_gauges_locked()

    # ---------------- read side ----------------

    def parked_len(self, session_id: str) -> int:
        """Kept length of a session's parked entry (0 if none) without
        touching LRU recency — the idle-park check must not keep its
        own candidates perpetually fresh."""
        with self._lock:
            entry = self._entries.get(session_id)
            return entry.kept if entry is not None else 0

    def note_lookup(self, restored: bool) -> None:
        """One fresh-slot admission consulted the pool; ``restored``
        when the kept prefix actually came back from host RAM."""
        with self._lock:
            self._lookups += 1
            if restored:
                self._hits += 1
                self._m_restored.inc()
                self._n_restored += 1
            self._m_hit_ratio.set(self._hits / self._lookups)

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "sessions": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": self.budget_bytes,
                "ttl_s": self.ttl_s,
                "parked_total": self._n_parked,
                "restored_total": self._n_restored,
                "evicted_total": self._n_evicted,
                "rejected_total": self._n_rejected,
                "restore_lookups": self._lookups,
                "restore_hits": self._hits,
                "restore_hit_ratio": (self._hits / self._lookups
                                      if self._lookups else None),
            }

    def snapshot(self) -> list[dict[str, Any]]:
        """Per-session parked-entry view for /debug/requests."""
        now = self._clock()
        with self._lock:
            return [{
                "session_id": e.session_id,
                "tokens": e.kept,
                "bytes": e.nbytes,
                "parked_s": round(now - e.parked_at, 3),
                "idle_s": round(now - e.last_used, 3),
                "prestaged": e.k_dev is not None,
            } for e in self._entries.values()]

    def _update_gauges_locked(self) -> None:
        self._m_bytes.set(self._bytes)
        self._m_sessions.set(len(self._entries))
