"""Fault injection and recovery verification (docs/RESILIENCE.md).

``failpoints`` is the named-failpoint registry every resilience seam in
the stack fires through; ``tests/test_chaos.py`` is the suite that
drives injected faults through the full stack and asserts the global
recovery invariants.
"""

from fasttalk_tpu.resilience.failpoints import (CATALOG, FaultCrash,
                                                FaultInjected)

__all__ = ["CATALOG", "FaultCrash", "FaultInjected"]
