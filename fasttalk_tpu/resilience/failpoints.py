"""Named-failpoint fault injection: prove the recovery paths, not just
ship them.

The last six robustness layers (supervisor restart, circuit breaker,
watchdog force_fail, router failover, KV park/restore, admission
shedding) were each verified by hand-crafted unit mocks. This module is
the common injection seam that lets one *declared* fault exercise the
real stack end to end: every resilience-relevant host-side boundary
fires a **named failpoint**, and an activated rule can turn that call
into an error, a delay, a hang, a corruption or a thread crash —
deterministically, probabilistically, or for the Nth hit only.

Zero hot-path overhead when disabled
------------------------------------
Every call site guards with the module-level flag::

    from fasttalk_tpu.resilience import failpoints as _fp
    ...
    if _fp.enabled:
        _fp.fire("engine.decode.dispatch", request_id=rid)

With no active rules ``enabled`` is ``False`` and the seam costs one
attribute load + branch — nothing else runs, no lock is taken, and no
failpoint code is reachable from inside any jitted program (all sites
are host-side dispatch boundaries; the device graphs are byte-identical
with the subsystem on or off).

Activation
----------
- ``FAULT_POINTS`` env spec, validated by ``utils.config.Config`` at
  startup (a bad spec is a named config error, never a silently
  disabled drill).
- ``POST /debug/fault`` on the monitoring port — **off by default**
  (``FAULT_HTTP=true`` enables it; never in production).

Spec grammar (one line, documented in docs/RESILIENCE.md)::

    FAULT_POINTS ::= clause ("," clause)*
    clause       ::= point "=" action (";" param)*
    action       ::= "error" | "hang" | "corrupt" | "crash_thread"
                   | "delay_ms:" INT
    param        ::= "p=" FLOAT    (fire probability, default 1.0)
                   | "count=" INT  (max fires, default unlimited)
                   | "after=" INT  (skip the first N matching hits)
                   | "match=" STR  (substring of any ctx value, e.g.
                                    a request or session id)

Example::

    FAULT_POINTS="engine.decode.dispatch=error;count=1,\
kv.park.copy=delay_ms:250;p=0.5"

Actions
-------
- ``error``        raise ``FaultInjected`` (or the seam's ``exc=``
                   class, so remote seams raise the transport error
                   type their retry machinery classifies).
- ``delay_ms:N``   sleep N ms at the seam (slowness, not failure).
- ``hang``         block until the rule is cleared (or
                   ``FAULT_HANG_MAX_S``, default 300) — what a wedged
                   device call or dead peer looks like.
- ``corrupt``      ``fire`` returns ``"corrupt"``; seams that can
                   meaningfully corrupt their payload do so, others
                   treat it as a no-op.
- ``crash_thread`` raise ``FaultCrash`` — a ``BaseException`` subclass
                   that escapes every scoped ``except Exception``
                   handler, killing the owning thread the way a real
                   interpreter-level fault would. Only the engine
                   loop's top-level handler catches it (a thread crash
                   there must still terminal-event in-flight requests).

Every fire increments ``fault_injected_total`` (plus a per-point
``fault_injected_<point>_<action>_total``) and emits a coalesced
``fault_injection`` event, which the flight recorder's bundles carry —
an incident capture always shows whether the incident was injected.
"""

from __future__ import annotations

import os
import random
import threading
import time
from dataclasses import dataclass, field
from typing import Any

from fasttalk_tpu.utils.logger import get_logger

log = get_logger("resilience.failpoints")

# Module-level fast-path flag: call sites read this BEFORE calling
# fire(). Updated (under _lock) whenever rules are activated/cleared.
enabled: bool = False

# The closed catalog of injection points. scripts/check_failpoints.py
# statically verifies (a) every name here is fired by at least one
# call site, (b) every fire() call site uses a name from here, and
# (c) every name is injected by at least one chaos test.
CATALOG: dict[str, str] = {
    "engine.loop.tick":
        "top of every engine-thread loop iteration (crash/hang the "
        "engine thread itself)",
    "engine.decode.dispatch":
        "before a jitted K-step decode call is dispatched",
    "engine.prefill.dispatch":
        "before a prefill device call (chunked and batched paths)",
    "engine.retire.fetch":
        "the blocking wait on a retired decode call's token fetch",
    "kv.block_alloc":
        "paged-KV device block-pool allocation (KV_LAYOUT=paged): "
        "exhaust the pool mid-prefill/decode",
    "kv.park.copy":
        "device->host fetch of a parked session's KV rows (copy "
        "thread)",
    "kv.prestage.copy":
        "best-effort host->device prestage of a parked entry",
    "kv.restore.dispatch":
        "host->device restore of parked KV at admission",
    "remote.connect":
        "remote backend HTTP connect, pre-first-byte (vllm/ollama)",
    "remote.stream":
        "remote backend response stream, per chunk",
    "router.probe":
        "router health/load probe of one replica (error = the probe "
        "cannot reach it: a network partition as the router sees it)",
    "router.place":
        "router placement decision for one request (error sheds the "
        "placement the way a fully-partitioned fleet would)",
    "router.migrate_send":
        "cross-replica KV migration, source-side export of the parked "
        "entry",
    "router.migrate_recv":
        "cross-replica KV migration, target-side import (corrupt = "
        "the transferred entry fails validation and is refused)",
    "router.handoff":
        "disaggregated prefill->decode handoff, between the prefill "
        "leg finishing and the KV landing on the decode replica "
        "(error/hang = the settle fails or wedges: the stream must "
        "fall back to mixed placement with no client-visible error)",
    "serving.ws.send":
        "WebSocket frame send to a client",
    "spmd.send":
        "SPMD leader frame send to followers",
    "spmd.recv":
        "SPMD follower frame receive",
    "structured.compile":
        "structured-output FSM compile on the compiler worker",
}

_ACTIONS = ("error", "delay_ms", "hang", "corrupt", "crash_thread")

# Safety net for `hang`: a forgotten rule must not wedge a test run or
# a drill forever. Overridable for tests.
HANG_MAX_S = float(os.getenv("FAULT_HANG_MAX_S", "300") or 300)


class FaultInjected(RuntimeError):
    """The `error` action's default exception (seams may override the
    class via fire(exc=...) so their retry/classification machinery
    sees the transport error type it expects)."""


class FaultCrash(BaseException):
    """The `crash_thread` action: subclasses BaseException so it
    escapes every scoped ``except Exception`` handler and genuinely
    kills the owning thread — the engine loop's top-level handler is
    the single place that catches it (a crash there must still
    terminal-event in-flight requests and mark the thread stopped)."""


@dataclass
class Rule:
    point: str
    action: str
    arg_ms: float = 0.0        # delay_ms argument
    p: float = 1.0             # fire probability per matching hit
    count: int | None = None   # max fires (None = unlimited)
    after: int = 0             # matching hits to skip first
    match: str = ""            # substring of any ctx value
    hits: int = 0              # matching hits seen
    fired: int = 0             # times the action actually ran

    def to_dict(self) -> dict[str, Any]:
        return {"point": self.point, "action": self.action,
                "arg_ms": self.arg_ms, "p": self.p,
                "count": self.count, "after": self.after,
                "match": self.match, "hits": self.hits,
                "fired": self.fired}


_lock = threading.Lock()
_rules: dict[str, list[Rule]] = {}
_spec: str = ""  # the spec text the active rules came from


def parse_spec(spec: str) -> list[Rule]:
    """Parse a FAULT_POINTS spec into rules. Raises ValueError naming
    every problem (unknown point, unknown action, bad parameter) —
    Config surfaces these as startup errors."""
    rules: list[Rule] = []
    errs: list[str] = []
    for clause in (c.strip() for c in spec.split(",") if c.strip()):
        head, _, tail = clause.partition(";")
        point, sep, action = head.partition("=")
        point = point.strip()
        action = action.strip()
        if not sep:
            errs.append(f"clause {clause!r} must be point=action")
            continue
        if point not in CATALOG:
            errs.append(f"unknown failpoint {point!r} (known: "
                        f"{', '.join(sorted(CATALOG))})")
            continue
        arg_ms = 0.0
        if action.startswith("delay_ms:"):
            raw = action[len("delay_ms:"):]
            action = "delay_ms"
            try:
                arg_ms = float(raw)
                if arg_ms < 0:
                    raise ValueError
            except ValueError:
                errs.append(f"{point}: delay_ms argument must be a "
                            f"non-negative number, got {raw!r}")
                continue
        elif action == "delay_ms":
            # A bare delay_ms would parse as a 0 ms sleep — a silently
            # inert drill, the exact failure mode the validated spec
            # exists to prevent.
            errs.append(f"{point}: delay_ms requires an argument "
                        "(delay_ms:<milliseconds>)")
            continue
        if action not in _ACTIONS:
            errs.append(f"{point}: unknown action {action!r} (known: "
                        f"{', '.join(_ACTIONS)})")
            continue
        rule = Rule(point=point, action=action, arg_ms=arg_ms)
        ok = True
        for param in (p.strip() for p in tail.split(";") if p.strip()):
            key, psep, val = param.partition("=")
            if not psep:
                errs.append(f"{point}: parameter {param!r} must be "
                            "key=value")
                ok = False
                continue
            try:
                if key == "p":
                    rule.p = float(val)
                    if not 0.0 <= rule.p <= 1.0:
                        raise ValueError
                elif key == "count":
                    rule.count = int(val)
                    if rule.count < 1:
                        raise ValueError
                elif key == "after":
                    rule.after = int(val)
                    if rule.after < 0:
                        raise ValueError
                elif key == "match":
                    rule.match = val
                else:
                    errs.append(f"{point}: unknown parameter {key!r} "
                                "(known: p, count, after, match)")
                    ok = False
            except ValueError:
                errs.append(f"{point}: bad value {val!r} for {key}")
                ok = False
        if ok:
            rules.append(rule)
    if errs:
        raise ValueError("invalid FAULT_POINTS spec: " + "; ".join(errs))
    return rules


def activate(spec: str) -> list[Rule]:
    """Replace the active rule set with the parsed spec (empty spec =
    clear). Raises ValueError on a bad spec without touching the
    active rules."""
    global enabled, _spec
    rules = parse_spec(spec)
    with _lock:
        _rules.clear()
        for r in rules:
            _rules.setdefault(r.point, []).append(r)
        _spec = spec if rules else ""
        enabled = bool(_rules)
    if rules:
        log.warning(f"fault injection ACTIVE: {len(rules)} rule(s) "
                    f"from spec {spec!r}")
    return rules


def clear() -> None:
    """Deactivate every rule (also releases any in-progress hang)."""
    global enabled, _spec
    with _lock:
        _rules.clear()
        _spec = ""
        enabled = False


def describe() -> dict[str, Any]:
    """Active-rule + catalog view for GET /debug/fault and /health."""
    with _lock:
        rules = [r.to_dict() for rl in _rules.values() for r in rl]
    return {"enabled": enabled, "spec": _spec, "rules": rules,
            "catalog": dict(CATALOG)}


def active_points() -> list[str]:
    with _lock:
        return sorted(_rules)


def _rule_active(rule: Rule) -> bool:
    """True while `rule` is still in the active set (hang-release
    check; the identity test means clear()/activate() releases every
    parked hang)."""
    with _lock:
        return rule in _rules.get(rule.point, ())


def _select(name: str, ctx: dict[str, Any]) -> list[Rule]:
    """Pick the rules that fire for this hit (shared by fire and
    fire_async); notes metrics/events for each."""
    assert name in CATALOG, f"unregistered failpoint {name!r}"
    to_run: list[Rule] = []
    with _lock:
        rules = _rules.get(name)
        if not rules:
            return to_run
        for rule in rules:
            if rule.match and not any(
                    rule.match in str(v) for v in ctx.values()):
                continue
            rule.hits += 1
            if rule.hits <= rule.after:
                continue
            if rule.count is not None and rule.fired >= rule.count:
                continue
            if rule.p < 1.0 and random.random() >= rule.p:
                continue
            rule.fired += 1
            to_run.append(rule)
    for rule in to_run:
        _note_fired(rule, ctx)
    return to_run


def fire(name: str, exc: type | None = None, **ctx: Any) -> str | None:
    """Evaluate the active rules for failpoint ``name``. Call sites
    MUST guard with ``if failpoints.enabled:`` — that guard is the
    zero-overhead-off contract. Seams that run on the asyncio event
    loop must use :func:`fire_async` instead (a blocking sleep there
    would freeze every stream AND the /debug/fault endpoint needed to
    clear the rule).

    ``exc``: exception class the `error` action raises instead of
    FaultInjected (seams pass their transport error type so retry/
    breaker classification sees a realistic failure).
    ``ctx``: request_id/session_id/... strings the `match` predicate
    tests against.

    Returns ``"corrupt"`` when a corrupt rule fired (the seam decides
    what corruption means), else None.
    """
    out: str | None = None
    for rule in _select(name, ctx):
        if rule.action == "delay_ms":
            time.sleep(rule.arg_ms / 1000.0)
        elif rule.action == "hang":
            deadline = time.monotonic() + HANG_MAX_S
            while _rule_active(rule) and time.monotonic() < deadline:
                time.sleep(0.02)
        else:
            out = _act(rule, name, exc) or out
    return out


async def fire_async(name: str, exc: type | None = None,
                     **ctx: Any) -> str | None:
    """fire() for seams running on the asyncio event loop (WS send,
    remote connect/stream): delay and hang YIELD via asyncio.sleep,
    so one hung stream stays one hung stream — other sessions,
    /health and the /debug/fault clear path keep running. The
    non-sleeping actions share _act with fire(), so sync and async
    seams cannot drift."""
    import asyncio

    out: str | None = None
    for rule in _select(name, ctx):
        if rule.action == "delay_ms":
            await asyncio.sleep(rule.arg_ms / 1000.0)
        elif rule.action == "hang":
            deadline = time.monotonic() + HANG_MAX_S
            while _rule_active(rule) and time.monotonic() < deadline:
                await asyncio.sleep(0.02)
        else:
            out = _act(rule, name, exc) or out
    return out


def _act(rule: Rule, name: str, exc: type | None) -> str | None:
    """The non-sleeping actions (corrupt / crash_thread / error),
    shared verbatim by fire and fire_async."""
    if rule.action == "corrupt":
        return "corrupt"
    if rule.action == "crash_thread":
        raise FaultCrash(f"fault injected at {name}: crash_thread")
    cls = exc if exc is not None else FaultInjected
    raise cls(f"fault injected at {name}: error")


def _note_fired(rule: Rule, ctx: dict[str, Any]) -> None:
    """Metrics + event per fire. Imported lazily-cached singletons;
    never lets observability failures mask the injected fault."""
    try:
        from fasttalk_tpu.observability.events import get_events
        from fasttalk_tpu.utils.metrics import get_metrics

        m = get_metrics()
        m.counter("fault_injected_total",
                  "fault-injection actions executed (all points)").inc()
        slug = rule.point.replace(".", "_")
        m.counter(f"fault_injected_{slug}_{rule.action}_total",
                  f"injected {rule.action} at {rule.point}").inc()
        get_events().emit(
            "fault_injection", severity="warning", coalesce_s=5.0,
            coalesce_key=f"{rule.point}:{rule.action}",
            point=rule.point, action=rule.action, fired=rule.fired,
            **{k: str(v) for k, v in ctx.items()})
        log.warning(f"failpoint fired: {rule.point} -> {rule.action} "
                    f"(fire #{rule.fired})")
    except Exception:  # pragma: no cover - observability must not mask
        pass


def _init_from_env() -> None:
    """Best-effort import-time activation from FAULT_POINTS. A bad
    spec logs an error and stays DISABLED here — utils.config.Config
    validates the same spec and turns it into a startup error, so a
    served process can never run with a typo'd drill silently
    dropped."""
    spec = os.getenv("FAULT_POINTS", "").strip()
    if not spec:
        return
    try:
        activate(spec)
    except ValueError as e:
        log.error(f"FAULT_POINTS ignored: {e}")


_init_from_env()
