"""Elastic replica scaling: spawn and retire fleet replicas on load.

The launcher attaches an ``ElasticScaler`` to the FleetRouter when
``FLEET_SCALE_MAX`` exceeds the base fleet size (docs/ROUTER.md
"Elastic replicas"). Decisions reuse the signals the stack already
publishes — the scheduler's queue depth (PR 2 admission control) and
the SLO engine's burn-rate alert states (PR 3) — so the scaler adds no
new health protocol, just a control loop:

- **Scale up** when aggregate queued work reaches
  ``FLEET_SCALE_UP_QUEUE`` or any SLO class is page-burning, and the
  fleet is under ``FLEET_SCALE_MAX``. A new in-process replica is
  built, started, probed and registered; placement starts sending it
  work on the next request.
- **Scale down** when the whole fleet has been idle (no queued, no
  running work) for ``FLEET_SCALE_DOWN_IDLE_S`` and the fleet is above
  ``FLEET_SCALE_MIN``. Scale-down is **drain-then-migrate**: the
  victim stops taking placements, its parked sessions' KV migrates to
  survivors (their next turn restores — the retirement is
  client-invisible), its in-flight streams finish in place, and only
  then is the replica removed and shut down.

Exactly one membership change is in flight at a time, and the check
loop is clock-injectable so tests drive it deterministically.
"""

from __future__ import annotations

import inspect
import threading
import time
from typing import Any, Callable

from fasttalk_tpu.observability.events import get_events
from fasttalk_tpu.router.disagg import (ROLE_DECODE, ROLE_MIXED,
                                        ROLE_PREFILL, role_of,
                                        tier_stats)
from fasttalk_tpu.router.replica import ReplicaHandle
from fasttalk_tpu.router.router import FleetRouter
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("router.elastic")


class ElasticScaler:
    """Queue-depth + SLO-burn driven fleet sizing over a FleetRouter.

    In a role-split fleet (router/disagg.py) the two tiers scale
    INDEPENDENTLY off their own saturation signals: the prefill tier
    off its aggregate queue depth (its work WAITS by design — depth is
    the whole signal), the decode tier off queue depth, SLO page-burn
    or slot occupancy crossing ``DECODE_OCCUPANCY_UP`` (decode
    saturates by filling slots long before it queues). Scale-up
    preserves the starved tier's role on the new replica; scale-down
    never retires the last replica of a tier. All-mixed fleets take
    the original single-signal path unchanged."""

    # Decode-tier scale-up trigger: fraction of the tier's decode
    # slots running. Queue depth alone under-fires for decode — slots
    # fill and streams slow down (inter-token latency) before the
    # scheduler queue grows.
    DECODE_OCCUPANCY_UP = 0.9

    def __init__(self, router: FleetRouter,
                 build_replica: Callable[[str], ReplicaHandle], *,
                 min_replicas: int = 1, max_replicas: int = 2,
                 up_queue_depth: int = 8,
                 down_idle_s: float = 120.0,
                 check_interval_s: float = 5.0,
                 slo_alerts: Callable[[], dict] | None = None,
                 clock=time.monotonic):
        self.router = router
        self.build_replica = build_replica
        self.min_replicas = max(1, min_replicas)
        self.max_replicas = max(self.min_replicas, max_replicas)
        self.up_queue_depth = max(1, up_queue_depth)
        self.down_idle_s = down_idle_s
        self.check_interval_s = check_interval_s
        self._slo_alerts = slo_alerts
        self._clock = clock
        self._idle_since: float | None = None
        self._pending_down: str | None = None  # replica draining out
        self._seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._events = get_events()
        m = get_metrics()
        self._m_up = m.counter(
            "router_scale_up_total",
            "replicas added by the elastic scaler")
        self._m_down = m.counter(
            "router_scale_down_total",
            "replicas retired by the elastic scaler (drain-then-"
            "migrate, client-invisible)")

    # ---------------- lifecycle ----------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="router-elastic",
                                        daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _loop(self) -> None:
        while not self._stop.wait(self.check_interval_s):
            try:
                self.check_once()
            except Exception as e:  # the control loop must never die
                log.error(f"elastic check failed: {e}", exc_info=True)

    # ---------------- the control decision ----------------

    def _fleet_load(self) -> tuple[int, int]:
        """(queued, running) across the fleet, from the same stats the
        probes already read."""
        stats = self.router.get_stats()
        return (int(stats.get("waiting", 0) or 0),
                int(stats.get("running", 0) or 0))

    def _slo_paging(self) -> bool:
        if self._slo_alerts is None:
            return False
        try:
            return any(v == "page"
                       for v in (self._slo_alerts() or {}).values())
        except Exception:
            return False

    def check_once(self) -> dict[str, Any]:
        """One control-loop pass (public + synchronous for tests).
        Returns a decision summary."""
        now = self._clock()
        self._reap_pending_down()
        live = [h for h in self.router.replicas
                if h.replica_id != self._pending_down]
        n = len(live)
        waiting, running = self._fleet_load()
        paging = self._slo_paging()
        decision = "hold"
        if self._pending_down is not None:
            # A retirement is still in flight (victim's streams
            # finishing). Exactly one membership change at a time:
            # hold here — a load spike just waits one reap (the
            # next pass scales up once the victim is gone, and the
            # victim's capacity is still serving its own streams
            # meanwhile).
            pass
        elif n < self.min_replicas:
            decision = self._scale_up("below_min")
        elif (waiting >= self.up_queue_depth or paging
              or self._decode_saturated(live)) \
                and n < self.max_replicas:
            decision = self._scale_up(
                "slo_burn" if paging else (
                    "queue_depth" if waiting >= self.up_queue_depth
                    else "decode_occupancy"),
                role=self._starved_role(live, waiting, paging),
                waiting=waiting)
        elif waiting == 0 and running == 0:
            if self._idle_since is None:
                self._idle_since = now
            elif (self.down_idle_s > 0
                  and now - self._idle_since >= self.down_idle_s
                  and n > self.min_replicas
                  and self._pending_down is None):
                decision = self._initiate_down()
        else:
            self._idle_since = None
        return {"decision": decision, "replicas": n,
                "waiting": waiting, "running": running,
                "paging": paging, "pending_down": self._pending_down}

    # ---------------- role-split tier signals (router/disagg.py) ----

    def _decode_saturated(self, live: list[ReplicaHandle]) -> bool:
        """Decode-tier slot occupancy at/over ``DECODE_OCCUPANCY_UP``
        — the decode tier's own saturation signal in a role-split
        fleet (occupancy comes from the replicas' last probe; an
        unprobed fleet reads as not saturated)."""
        if all(role_of(h) == ROLE_MIXED for h in live):
            return False
        return any(t.get("occupancy") is not None
                   and t["occupancy"] >= self.DECODE_OCCUPANCY_UP
                   for role, t in tier_stats(live).items()
                   if role != ROLE_PREFILL)

    def _starved_role(self, live: list[ReplicaHandle], waiting: int,
                      paging: bool) -> str:
        """Which tier the new replica should join. Mixed fleets grow
        mixed (unchanged behaviour). In a role-split fleet the prefill
        tier wins only when its OWN queue crossed the threshold and
        the decode tier is not in distress — decode latency is the
        user-facing signal, so ties go to decode."""
        if all(role_of(h) == ROLE_MIXED for h in live):
            return ROLE_MIXED
        tiers = tier_stats(live)
        pf_waiting = tiers.get(ROLE_PREFILL, {}).get("waiting", 0)
        if pf_waiting >= self.up_queue_depth and not paging \
                and not self._decode_saturated(live):
            return ROLE_PREFILL
        return ROLE_DECODE

    def _build(self, replica_id: str, role: str) -> ReplicaHandle:
        """Invoke the launcher's builder, passing the role through
        when it accepts one (older builders — and the test suite's
        1-arg lambdas — predate roles; their handles get the role
        stamped on after the fact, engine mirror included, so
        scale-up preserves the starved tier either way)."""
        try:
            wants_role = len(inspect.signature(
                self.build_replica).parameters) >= 2
        except (TypeError, ValueError):
            wants_role = False
        if wants_role:
            handle = self.build_replica(replica_id, role)
        else:
            handle = self.build_replica(replica_id)
        if role_of(handle) != role:
            handle.role = role
            try:
                handle.engine.role = role
            except Exception:
                pass
        return handle

    # ---------------- scale up ----------------

    def _scale_up(self, reason: str, role: str = ROLE_MIXED,
                  **attrs: Any) -> str:
        self._seq += 1
        replica_id = f"elastic-{self._seq}"
        if role != ROLE_MIXED:
            attrs["role"] = role
        try:
            handle = self._build(replica_id, role)
            handle.engine.start()
            handle.probe_now()
            self.router.add_replica(handle)
        except Exception as e:
            log.error(f"scale-up failed: {e}", exc_info=True)
            self._events.emit("router_scale", severity="critical",
                              action="up_failed", reason=reason,
                              error=str(e)[:200])
            return "up_failed"
        self._m_up.inc()
        self._idle_since = None
        self._events.emit("router_scale", severity="warning",
                          action="up", replica=replica_id,
                          reason=reason, fleet=len(self.router.replicas),
                          **attrs)
        log.info(f"scaled UP ({reason}): added {replica_id}, fleet is "
                 f"now {len(self.router.replicas)}")
        return "up"

    # ---------------- scale down (drain-then-migrate) ----------------

    def _initiate_down(self) -> str:
        """Pick a victim and start its client-invisible retirement:
        drain_replica migrates its parked KV to survivors and stops
        placements; the handle is reaped once its streams finish.

        Remote replicas (ROUTER_BACKENDS) are NEVER victims: the
        scaler's build_replica only makes in-process engines, so a
        retired remote backend could not come back on the next
        scale-up — the static fleet would degrade permanently."""
        from fasttalk_tpu.router.replica import RemoteReplicaHandle

        candidates = [h for h in self.router.replicas
                      if h.available()
                      and not isinstance(h, RemoteReplicaHandle)]
        if any(role_of(h) != ROLE_MIXED for h in self.router.replicas):
            # Role-split fleet: never retire the last available
            # replica of a tier — an empty prefill tier silently turns
            # every long prompt into a fallback, an empty decode tier
            # cannot serve at all.
            tier_avail: dict[str, int] = {}
            for h in self.router.replicas:
                if h.available():
                    tier_avail[role_of(h)] = \
                        tier_avail.get(role_of(h), 0) + 1
            candidates = [h for h in candidates
                          if tier_avail.get(role_of(h), 0) > 1]
        if not candidates \
                or len([h for h in self.router.replicas
                        if h.available()]) <= self.min_replicas:
            return "hold"
        victim = min(candidates, key=lambda h: h.load_score())
        summary = self.router.drain_replica(victim.replica_id)
        self._pending_down = victim.replica_id
        self._events.emit("router_scale", severity="warning",
                          action="down_draining",
                          replica=victim.replica_id,
                          migrated_kv=summary.get("migrated_kv", 0),
                          busy=len(summary.get("busy_sessions", [])))
        log.info(f"scaling DOWN: draining {victim.replica_id} "
                 f"(migrated_kv={summary.get('migrated_kv', 0)})")
        self._reap_pending_down()
        return "down_draining"

    def _reap_pending_down(self) -> None:
        """Finish a retirement whose streams have drained: remove the
        replica from the router and shut its engine down."""
        rid = self._pending_down
        if rid is None:
            return
        handle = next((h for h in self.router.replicas
                       if h.replica_id == rid), None)
        if handle is None:  # already gone (operator removed it)
            self._pending_down = None
            return
        try:
            busy = len(handle.inflight) \
                or int(handle.engine.pending_requests() or 0)
        except Exception:
            busy = 0
        if busy:
            return  # streams still finishing in place
        try:
            self.router.remove_replica(rid)
        except ValueError:
            return  # last replica — never remove
        self._pending_down = None
        self._m_down.inc()
        try:
            handle.engine.shutdown()
        except Exception as e:
            log.error(f"retired replica {rid} shutdown error: {e}")
        self._events.emit("router_scale", severity="warning",
                          action="down", replica=rid,
                          fleet=len(self.router.replicas))
        log.info(f"scaled DOWN: retired {rid}, fleet is now "
                 f"{len(self.router.replicas)}")

    def stats(self) -> dict[str, Any]:
        return {
            "min_replicas": self.min_replicas,
            "max_replicas": self.max_replicas,
            "up_queue_depth": self.up_queue_depth,
            "down_idle_s": self.down_idle_s,
            "pending_down": self._pending_down,
            "scale_ups": self._m_up.value,
            "scale_downs": self._m_down.value,
        }
