"""Replica handles: one engine replica as the router sees it.

A handle wraps one backend — an in-process ``EngineBase`` (the common
CPU-fleet/bench shape, and the dp-style multi-engine shape on real
hardware) or a remote FastTalk server reached over HTTP (the same
``remote.py`` client protocol the legacy providers speak) — and keeps
the router-side view of it: health state, the latest probe's load
signals, and the set of requests currently routed here.

Health is a small state machine:

    healthy ⇄ degraded      (probe signals: overload state, SLO burn)
    any     → dead          (``dead_probes`` consecutive probe failures,
                             or a stream failing while check_connection()
                             is already False — fast-path detection so a
                             mid-stream death never waits a probe period)
    dead    → healthy       (a later probe finds the engine back — e.g.
                             the launcher's supervised engine restart)

``draining`` is orthogonal to health: a draining replica finishes what
it has but takes no new placements (docs/ROUTER.md).

Probes are synchronous by design — the router runs them on its own
daemon thread (in-proc probes are a few dict reads; remote probes are
one short HTTP GET), never on the serving event loop. ``clock`` is
injectable for deterministic tests, like the scheduler's.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from fasttalk_tpu.engine.engine import EngineBase
from fasttalk_tpu.resilience import failpoints as _fp
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("router.replica")

STATE_HEALTHY = "healthy"
STATE_DEGRADED = "degraded"
STATE_DEAD = "dead"

# Load-score weights: a replica's score is the expected queueing cost of
# placing one more request on it. Queue depth dominates (each queued
# request is one service time of wait); overload states add the
# scheduler's own judgement; an SLO page means the replica is already
# breaking promises. Lower score wins.
_OVERLOAD_PENALTY = {"healthy": 0.0, "pressured": 2.0,
                     "shedding": 8.0, "draining": float("inf")}
_SLO_PENALTY = {"ok": 0.0, "warn": 2.0, "page": 8.0}


class ReplicaHandle:
    """One in-process engine replica, as the router tracks it."""

    def __init__(self, replica_id: str, engine: EngineBase, *,
                 role: str = "mixed", dead_probes: int = 2,
                 clock=time.monotonic):
        self.replica_id = replica_id
        self.engine = engine
        # Disaggregated-serving role (router/disagg.py): placement
        # filters by it, /fleet surfaces it. Mirrored onto the engine
        # so an in-proc prefill replica enforces its zero-decode-slot
        # guarantee itself (remote engines are client stubs — the
        # remote server enforces its own configured role).
        self.role = role
        engine.role = role
        self.dead_probes = max(1, dead_probes)
        self._clock = clock
        self._lock = threading.Lock()
        self.state = STATE_HEALTHY
        self.draining = False
        # Why the replica is dead ("probe" = consecutive probe
        # failures, the network-partition signature; "stream" = a
        # stream failed with the backend unreachable). None while not
        # dead. The router emits `router_partition` for probe deaths.
        self.dead_reason: str | None = None
        # Last begin_drain/drain_replica failure against this replica
        # (None = drains clean) — surfaced on GET /fleet so a stuck
        # drain is visible, not a log line (docs/ROUTER.md).
        self.drain_error: str | None = None
        self._consec_failures = 0
        self.last_probe: dict[str, Any] = {}
        self.last_probe_at: float | None = None
        # Request ids currently streaming from this replica (router-side
        # bookkeeping; feeds the load score between probes).
        self.inflight: set[str] = set()
        self.placements = 0          # lifetime placements (stats)
        self.failovers = 0           # streams that died here (stats)

    # ---------------- probing ----------------

    def probe_now(self) -> dict[str, Any]:
        """One synchronous health/load probe. Updates ``state`` and
        ``last_probe``; returns the signal dict. Never raises."""
        try:
            if _fp.enabled:
                # Chaos seam: `error` here IS a network partition as
                # the router experiences one — the backend may be
                # perfectly alive, the router just cannot see it.
                _fp.fire("router.probe", replica=self.replica_id)
            alive = self.engine.check_connection()
        except Exception:
            alive = False
        if not alive:
            return self._probe_failed("backend not connected")
        try:
            signals = self._collect_signals()
        except Exception as e:  # a flaky stats surface is not a death
            signals = {"error": f"stats probe failed: {e}"}
        with self._lock:
            self._consec_failures = 0
            recovered = self.state == STATE_DEAD
            self.dead_reason = None
            self.state = (STATE_DEGRADED
                          if signals.get("overload_state")
                          in ("pressured", "shedding")
                          or signals.get("slo_alert") == "page"
                          else STATE_HEALTHY)
            self.last_probe = signals
            self.last_probe_at = self._clock()
        if recovered:
            log.info(f"replica {self.replica_id} recovered "
                     f"(state {self.state})")
        return signals

    def _collect_signals(self) -> dict[str, Any]:
        """Load signals from an in-proc engine's own stats surface —
        the same numbers /health and /stats publish, read directly.

        Deliberately NO slo_alert here: in-proc replicas share the
        process-wide SLO engine, so its alert state is identical for
        every replica and carries no per-replica routing information.
        The SLO placement penalty applies to remote replicas, whose
        /health body reports their own burn state."""
        stats = self.engine.get_stats() or {}
        sched = stats.get("scheduler") or {}
        slots = stats.get("slots") or {}
        return {
            "alive": True,
            "waiting": stats.get("waiting", 0) or 0,
            "running": (stats.get("running", slots.get("active", 0))
                        or 0),
            "slots_total": slots.get("total_slots"),
            "overload_state": sched.get("state", "healthy"),
            "estimated_wait_s": sched.get("estimated_wait_s", 0.0),
            "draining_backend": bool(sched.get("draining", False)),
        }

    def _probe_failed(self, reason: str) -> dict[str, Any]:
        with self._lock:
            self._consec_failures += 1
            died = (self.state != STATE_DEAD
                    and self._consec_failures >= self.dead_probes)
            if died:
                self.state = STATE_DEAD
                self.dead_reason = "probe"
            self.last_probe = {"alive": False, "error": reason}
            self.last_probe_at = self._clock()
        if died:
            log.warning(f"replica {self.replica_id} marked dead: "
                        f"{reason}")
        return self.last_probe

    def note_stream_failure(self) -> bool:
        """Fast-path death detection: a stream just failed here. If the
        backend is also unreachable, mark dead NOW instead of waiting
        out ``dead_probes`` probe periods. Returns True when this call
        transitioned the replica to dead."""
        try:
            alive = self.engine.check_connection()
        except Exception:
            alive = False
        with self._lock:
            self.failovers += 1
            if not alive and self.state != STATE_DEAD:
                self.state = STATE_DEAD
                self.dead_reason = "stream"
                self._consec_failures = self.dead_probes
                log.warning(f"replica {self.replica_id} marked dead "
                            "(stream failed and backend unreachable)")
                return True
        return False

    # ---------------- placement view ----------------

    def alive(self) -> bool:
        try:
            return bool(self.engine.check_connection())
        except Exception:
            return False

    def available(self) -> bool:
        """Eligible for NEW placements: not dead, not draining."""
        return self.state != STATE_DEAD and not self.draining

    def load_score(self) -> float:
        """Expected cost of placing one more request here (lower is
        better). Uses the latest probe's signals plus the router's own
        live in-flight count, so the score moves between probes."""
        with self._lock:
            p = dict(self.last_probe)
            inflight = len(self.inflight)
        if self.draining or p.get("draining_backend"):
            return float("inf")
        score = float(p.get("waiting", 0) or 0) + float(inflight)
        slots = p.get("slots_total")
        if slots:
            score += float(p.get("running", 0) or 0) / float(slots)
        score += _OVERLOAD_PENALTY.get(p.get("overload_state", "healthy"),
                                       0.0)
        score += _SLO_PENALTY.get(p.get("slo_alert", "ok"), 0.0)
        return score

    # ---------------- KV migration channel (router/migrate.py) ----

    # In-proc replicas hand the parked entry's numpy arrays over
    # directly through the engine seam; RemoteReplicaHandle overrides
    # with the /kv/parked HTTP wire form. All four run on the router's
    # migrate worker thread (never the event loop) and may raise — the
    # transfer classifies and the router falls back to re-prefill.

    def parked_info(self, session_id: str) -> tuple[int, int] | None:
        return self.engine.parked_kv_info(session_id)

    def export_parked(self, session_id: str,
                      traceparent: str | None = None):
        # traceparent is a wire concern: in-proc transfers already live
        # inside the process tracer, so the kwarg is accepted (one
        # transfer() call shape for both handle types) and ignored.
        return self.engine.export_parked_kv(session_id)

    def import_parked(self, entry, traceparent: str | None = None,
                      ) -> bool:
        return bool(self.engine.import_parked_kv(entry))

    def drop_parked(self, session_id: str) -> bool:
        return bool(self.engine.drop_parked_kv(session_id))

    # ---------------- fleet observability fan-out ----------------
    # (router/router.py stitched_trace / fleet_metrics / fleet_slo,
    # observability/fleetflight.py). In-proc replicas share the
    # router-front process's tracer, metrics registry and SLO engine —
    # their contribution is already in the local fragment/exposition,
    # so fetching from them would double-count. RemoteReplicaHandle
    # overrides with the serving-port HTTP surfaces.

    def fetch_trace(self, request_id: str,
                    trace_id: str = "") -> list[dict[str, Any]]:
        """Trace fragments this replica holds for a request ([] for
        in-proc: the local collect_fragments already saw them)."""
        return []

    def fetch_metrics(self) -> str | None:
        """Prometheus exposition text (None for in-proc: the shared
        registry is the local text)."""
        return None

    def fetch_slo(self) -> dict[str, Any] | None:
        """SLO report (None for in-proc: the shared engine's snapshot
        is the local report)."""
        return None

    def to_dict(self) -> dict[str, Any]:
        with self._lock:
            return {
                "replica_id": self.replica_id,
                "role": self.role,
                "state": self.state,
                "dead_reason": self.dead_reason,
                "draining": self.draining,
                "drain_error": self.drain_error,
                "inflight": len(self.inflight),
                "placements": self.placements,
                "failovers": self.failovers,
                "load_score": None,  # filled by caller outside the lock
                "last_probe": dict(self.last_probe),
                "last_probe_at": self.last_probe_at,
            }


class RemoteReplicaHandle(ReplicaHandle):
    """A replica reached over HTTP: another FastTalk server (its
    OpenAI-compatible /v1 surface carries generations via the existing
    ``remote.py`` client; its /health carries the probe signals).

    ``base_url`` is the serving root, e.g. ``http://replica-2:8000``.
    """

    def __init__(self, replica_id: str, base_url: str, model: str, *,
                 role: str = "mixed", dead_probes: int = 2,
                 probe_timeout_s: float = 3.0,
                 timeout_s: float = 600.0, max_inflight: int = 32,
                 admission_timeout_s: float = 30.0,
                 connect_retries: int = 2, clock=time.monotonic):
        from fasttalk_tpu.engine.remote import VLLMRemoteEngine

        self.base_url = base_url.rstrip("/")
        self.probe_timeout_s = probe_timeout_s
        engine = VLLMRemoteEngine(
            f"{self.base_url}/v1", model, timeout_s=timeout_s,
            max_inflight=max_inflight,
            admission_timeout_s=admission_timeout_s,
            connect_retries=connect_retries)
        super().__init__(replica_id, engine, role=role,
                         dead_probes=dead_probes, clock=clock)

    def probe_now(self) -> dict[str, Any]:
        import requests

        try:
            if _fp.enabled:
                # Chaos seam: the remote flavour of a partition — the
                # health GET never arrives.
                _fp.fire("router.probe", replica=self.replica_id)
            r = requests.get(f"{self.base_url}/health",
                             timeout=self.probe_timeout_s)
            body = r.json() if r.content else {}
        except Exception as e:
            return self._probe_failed(f"health probe failed: {e}")
        if r.status_code >= 500:
            return self._probe_failed(f"health returned {r.status_code}")
        sched = body.get("scheduler") or {}
        slo = body.get("slo") or {}
        signals = {
            "alive": True,
            "status": body.get("status"),
            "waiting": sched.get("depth", 0) or 0,
            "running": body.get("active_sessions", 0) or 0,
            "slots_total": None,
            "overload_state": sched.get("state", "healthy"),
            "estimated_wait_s": sched.get("estimated_wait_s", 0.0),
            "draining_backend": bool(sched.get("draining", False)),
            # Worst class alert ("page" beats "warn" beats "ok").
            "slo_alert": max(slo.values(), default="ok",
                             key=("ok", "warn", "page").index)
            if all(v in ("ok", "warn", "page") for v in slo.values())
            else "ok",
        }
        with self._lock:
            self._consec_failures = 0
            recovered = self.state == STATE_DEAD
            self.dead_reason = None
            self.state = (STATE_DEGRADED
                          if signals["overload_state"]
                          in ("pressured", "shedding")
                          or signals["slo_alert"] == "page"
                          else STATE_HEALTHY)
            self.last_probe = signals
            self.last_probe_at = self._clock()
        if recovered:
            log.info(f"replica {self.replica_id} recovered")
        return signals

    def alive(self) -> bool:
        # The remote engine's check_connection() probes /health itself;
        # state from the last probe is the cheaper, equivalent signal.
        return self.state != STATE_DEAD

    def note_stream_failure(self) -> bool:
        """No blocking liveness probe here — the base implementation's
        check_connection() would be a synchronous HTTP GET executed on
        the asyncio event loop mid-failover, freezing every other live
        stream for the TCP timeout. A stream failing against a remote
        replica (after the client's own pre-first-token retries) marks
        it dead immediately; the probe thread recovers it as soon as
        /health answers again."""
        with self._lock:
            self.failovers += 1
            if self.state != STATE_DEAD:
                self.state = STATE_DEAD
                self.dead_reason = "stream"
                self._consec_failures = self.dead_probes
                log.warning(f"replica {self.replica_id} marked dead "
                            "(stream failed)")
                return True
        return False

    # ---------------- KV migration over HTTP ----------------
    # The serving port's /kv/parked/{session_id} endpoints
    # (serving/server.py) carry the wire form from router/migrate.py.
    # Synchronous `requests` by design: these run on the router's
    # disposable migrate worker thread, which the router bounds with
    # ROUTER_MIGRATE_TIMEOUT_S — never on the event loop.

    MIGRATE_HTTP_TIMEOUT_S = 30.0

    def parked_info(self, session_id: str) -> tuple[int, int] | None:
        import requests

        r = requests.get(f"{self.base_url}/kv/parked/{session_id}",
                         params={"meta": "1"},
                         timeout=self.probe_timeout_s)
        if r.status_code != 200:
            return None
        body = r.json()
        return int(body["kept"]), int(body["nbytes"])

    def export_parked(self, session_id: str,
                      traceparent: str | None = None):
        import requests

        from fasttalk_tpu.router.migrate import deserialize_parked

        r = requests.get(f"{self.base_url}/kv/parked/{session_id}",
                         headers={"traceparent": traceparent}
                         if traceparent else None,
                         timeout=self.MIGRATE_HTTP_TIMEOUT_S)
        if r.status_code == 404:
            return None
        r.raise_for_status()
        return deserialize_parked(r.content)

    def import_parked(self, entry, traceparent: str | None = None,
                      ) -> bool:
        import requests

        from fasttalk_tpu.router.migrate import serialize_parked

        headers = {"Content-Type": "application/octet-stream"}
        if traceparent:
            headers["traceparent"] = traceparent
        r = requests.post(
            f"{self.base_url}/kv/parked/{entry.session_id}",
            data=serialize_parked(entry),
            headers=headers,
            timeout=self.MIGRATE_HTTP_TIMEOUT_S)
        return r.status_code == 200

    def drop_parked(self, session_id: str) -> bool:
        import requests

        r = requests.delete(f"{self.base_url}/kv/parked/{session_id}",
                            timeout=self.probe_timeout_s)
        return r.status_code == 200

    # ---------------- fleet observability fan-out ----------------

    def fetch_trace(self, request_id: str,
                    trace_id: str = "") -> list[dict[str, Any]]:
        """Fragments this replica's serving port holds for a request
        (GET /traces/{request_id}, serving/server.py). Raises on
        transport failure — the router classifies and keeps stitching
        from the replicas that answered."""
        import requests

        r = requests.get(f"{self.base_url}/traces/{request_id}",
                         params={"trace_id": trace_id}
                         if trace_id else None,
                         timeout=self.probe_timeout_s)
        if r.status_code == 404:
            return []
        r.raise_for_status()
        body = r.json()
        frags = body.get("fragments", [])
        for f in frags:
            f.setdefault("source", self.replica_id)
        return frags

    def fetch_metrics(self) -> str | None:
        import requests

        r = requests.get(f"{self.base_url}/metrics",
                         timeout=self.probe_timeout_s)
        r.raise_for_status()
        return r.text

    def fetch_slo(self) -> dict[str, Any] | None:
        import requests

        r = requests.get(f"{self.base_url}/slo",
                         timeout=self.probe_timeout_s)
        r.raise_for_status()
        return r.json()
