"""FleetRouter: health-aware routing across N engine replicas.

The scale-OUT tier the ROADMAP north star requires: one EngineBase-
shaped front that spreads sessions across a fleet of engine replicas
(in-process engines and/or remote FastTalk servers), the way
JetStream/llm-d-style deployments front their model servers. Because
the router IS an ``EngineBase``, the entire serving stack — WebSocket
server, OpenAI routes, breaker, drain-on-shutdown — runs unchanged on
top of it; the router slots in where a single engine used to be.

What it adds over a bare engine (docs/ROUTER.md):

- **Replica registry + probes.** A daemon thread probes every replica
  each ``probe_interval_s`` using the signals the stack already
  publishes (check_connection / get_stats for in-proc replicas, the
  /health body for remote ones) — no new health protocol.
- **Session affinity.** A session sticks to the replica holding its
  resident or host-parked KV (policy.py), so the PR-4 restore path
  keeps paying off across the fleet; new sessions place
  weighted-least-loaded (queue depth, overload state, SLO burn).
- **Failover.** A replica dying mid-stream triggers resume-on-survivor:
  the transcript re-prefills on a healthy replica, already-delivered
  text is trimmed from the new stream, and the client sees one
  ``resumed`` event — not an error. Pre-first-token failures re-route
  silently (nothing was delivered, the retry is idempotent); when no
  healthy replica remains the request sheds with ``retry_after``.
- **Coordinated drain.** ``drain_replica()`` stops placement to one
  replica, lets its in-flight streams finish, and migrates its idle
  parked sessions to a survivor — the fleet keeps serving through a
  rolling restart.
- **Cross-replica KV migration** (router/migrate.py). Drain, failover
  and rebalancing move a parked session's host-KV entry to the target
  replica's pool, so the next turn RESTORES (copy + delta prefill)
  instead of re-prefilling the transcript. The three-way decision —
  migrate vs re-prefill vs restore-local — is priced by the
  kvcache/policy.py EMAs with a migration-bandwidth term; transfers
  are bounded by ``ROUTER_MIGRATE_TIMEOUT_S`` and fall back to
  re-prefill on any failure with exact byte accounting on both pools.
- **Prefix-aware placement + elastic replicas.** Same-system-prompt
  tenants co-locate while nearly free (policy.py PREFIX_SLACK) to hit
  the shared-prefix stamp; router/elastic.py scales the fleet up on
  queue depth / SLO burn and down via drain-then-migrate
  (client-invisible).

Resume caveat: the survivor re-generates from the transcript, so with
temperature > 0 the continuation may diverge from what the dead replica
would have said; with greedy sampling it is identical. The overlap trim
is by character count of delivered text.
"""

from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import replace as _dc_replace
from typing import Any, AsyncGenerator

from fasttalk_tpu.engine.engine import (EngineBase, GenerationParams,
                                        raw_prompt_text)
from fasttalk_tpu.kvcache import RestorePolicy, kv_env_defaults
from fasttalk_tpu.kvcache.radix import chain_digest
from fasttalk_tpu.observability.events import get_events
from fasttalk_tpu.observability.trace import (current_traceparent,
                                              get_tracer)
import fasttalk_tpu.router.migrate as _migrate
from fasttalk_tpu.resilience import failpoints as _fp
from fasttalk_tpu.router.disagg import (DECODE_ROLES, ROLE_MIXED,
                                        ROLE_PREFILL, DisaggController,
                                        parse_roles, role_of,
                                        tier_stats)
from fasttalk_tpu.router.policy import AffinityMap, PlacementPolicy
from fasttalk_tpu.router.replica import (STATE_DEAD, ReplicaHandle,
                                         RemoteReplicaHandle)
from fasttalk_tpu.utils.errors import (AdmissionRejected, ErrorCategory,
                                       LLMServiceError)
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("router")

# LLMServiceError categories that indicate the REPLICA failed (connect
# refused, timeout, OOM) rather than the request being malformed —
# these are failover-eligible; validation/model errors propagate.
_FAULT_CATEGORIES = (ErrorCategory.CONNECTION, ErrorCategory.TIMEOUT,
                     ErrorCategory.RESOURCE)


class FleetRouter(EngineBase):
    """Engine-shaped front over a fleet of replicas."""

    def __init__(self, replicas: list[ReplicaHandle], *,
                 probe_interval_s: float = 2.0,
                 affinity_ttl_s: float = 600.0,
                 failover_retries: int = 2,
                 resume: bool = True,
                 migrate: bool = True,
                 migrate_timeout_s: float = 10.0,
                 prefix_affinity: bool = True,
                 disagg_prefill_min_tokens: int = 512,
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        ids = [h.replica_id for h in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas = list(replicas)
        self.probe_interval_s = probe_interval_s
        self.failover_retries = max(0, failover_retries)
        self.resume_enabled = resume
        self.migrate_enabled = migrate
        self.migrate_timeout_s = max(0.05, migrate_timeout_s)
        self._clock = clock
        # Three-way migrate/re-prefill/restore-local pricing
        # (kvcache/policy.py): migration bandwidth learned from this
        # router's own completed transfers, prefill throughput from the
        # fleet's done-event stats (prompt_tokens / ttft).
        self.kv_policy = RestorePolicy(
            min_tokens=int(kv_env_defaults()["min_tokens"]))
        # Disaggregated prefill/decode (router/disagg.py): the handoff
        # decision + its learned wire-cost model, sharing the same
        # pricing EMAs as drain/failover migration. Dormant (and
        # byte-identical to the pre-disagg router) until a replica
        # carries a non-mixed role.
        self.disagg = DisaggController(
            self.kv_policy,
            prefill_min_tokens=disagg_prefill_min_tokens)
        # request_id -> (prefill handle, sub-request id) while a
        # handoff's prefill leg is in flight — cancel() forwards there.
        self._handoff_streams: dict[str, tuple[ReplicaHandle, str]] = {}
        # First in-proc replica's tokenizer, resolved lazily: the
        # router has no model of its own, but the threshold routing
        # needs a prompt-length estimate (falls back to chars/4 for
        # all-remote fleets).
        self._tok: Any = False  # False = unresolved, None = none found
        self.affinity = AffinityMap(ttl_s=affinity_ttl_s, clock=clock)
        self.policy = PlacementPolicy(
            self.affinity, prefix_affinity=prefix_affinity,
            on_prefix_hit=lambda: self._m_prefix.inc())
        self._routes: dict[str, tuple[str, ReplicaHandle]] = {}
        self._cancelled: set[str] = set()
        self._draining = False
        self._started = False
        self._probe_thread: threading.Thread | None = None
        self._probe_stop = threading.Event()
        self._events = get_events()
        # Router spans carry component="router" so a stitched trace
        # (observability/stitch.py) keeps the fleet's hops apart from
        # the replicas' queue_wait/prefill/decode_step spans even when
        # everything shares one in-proc process tracer.
        self._tracer = get_tracer().scoped("router")
        m = get_metrics()
        self._m_replicas = m.gauge(
            "router_replicas", "replicas registered with the router")
        self._m_available = m.gauge(
            "router_replicas_available",
            "replicas currently placeable (not dead, not draining)")
        self._m_placements = m.counter(
            "router_placements_total", "requests placed on a replica")
        self._m_affinity_hits = m.counter(
            "router_affinity_hits_total",
            "placements that reused the session's pinned replica")
        self._m_failovers = m.counter(
            "router_failovers_total",
            "streams that failed on a replica and were re-routed")
        self._m_resumes = m.counter(
            "router_resumes_total",
            "mid-stream failovers resumed on a survivor (client saw a "
            "resumed event, not an error)")
        self._m_sheds = m.counter(
            "router_sheds_total",
            "requests shed by the router (no placeable replica)")
        self._m_migrations = m.counter(
            "router_migrations_total",
            "parked-KV entries migrated between replicas")
        self._m_migration_failures = m.counter(
            "router_migration_failures_total",
            "cross-replica KV migrations that failed (both pools left "
            "with exact byte accounting; session falls back to "
            "re-prefill)")
        self._m_migration_bytes = m.counter(
            "router_migration_bytes",
            "parked-KV bytes moved between replica pools")
        self._m_migration_ms = m.histogram(
            "router_migration_ms",
            "cross-replica KV migration latency (export + transfer + "
            "import)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000,
                     4000, 10000))
        self._m_drain_errors = m.counter(
            "router_drain_errors_total",
            "per-replica drain calls that failed (partial drain — "
            "surfaced on GET /fleet)")
        self._m_partitions = m.counter(
            "router_partitions_total",
            "replicas declared dead by consecutive probe failures "
            "(the network-partition signature)")
        self._m_prefix = m.counter(
            "router_prefix_colocations_total",
            "placements co-located with their shared-prefix tenant "
            "replica (prefix-stamp reuse)")
        self._m_handoffs = m.counter(
            "router_disagg_handoffs_total",
            "disaggregated prefill->decode handoffs completed (prefill "
            "tier computed the KV, the decode tier restored it)")
        self._m_handoff_ms = m.histogram(
            "router_disagg_handoff_ms",
            "disagg handoff settle latency (park wait + KV transfer "
            "to the decode replica; the prefill itself is not in "
            "here — TTFT = prefill + this)",
            buckets=(1, 2, 4, 8, 16, 32, 64, 128, 256, 512, 1000,
                     4000, 10000))
        self._m_handoff_fallbacks = m.counter(
            "router_disagg_fallback_total",
            "streams that fell back to mixed placement (pricing said "
            "re-prefill, no prefill replica, or the handoff "
            "failed/hung — zero client-visible error frames either "
            "way)")
        self._m_replicas.set(len(self.replicas))

    # ---------------- lifecycle ----------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for h in self.replicas:
            try:
                h.engine.start()
            except Exception as e:
                log.error(f"replica {h.replica_id} failed to start: {e}")
        self.probe_once()
        if self.probe_interval_s > 0:
            self._probe_stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="router-probe", daemon=True)
            self._probe_thread.start()

    def shutdown(self) -> None:
        self._started = False
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        for h in self.replicas:
            try:
                h.engine.shutdown()
            except Exception as e:
                log.error(f"replica {h.replica_id} shutdown error: {e}")

    def warmup(self, level: str = "off") -> None:
        for h in self.replicas:
            h.engine.warmup(level)

    def begin_drain(self) -> None:
        """Fleet-wide drain (server shutdown): every replica stops
        admitting; queued and in-flight work finishes. A replica whose
        drain call fails is a PARTIAL drain — not a log line: it emits
        a ``router_drain_error`` event, bumps the counter, and latches
        ``drain_error`` on the handle so GET /fleet shows the operator
        which replica is stuck."""
        self._draining = True
        self._events.emit("router_drain", severity="warning",
                          scope="fleet", replicas=len(self.replicas))
        for h in self.replicas:
            h.draining = True
            self._drain_engine(h)

    def _drain_engine(self, handle: ReplicaHandle) -> bool:
        """begin_drain one replica's engine, recording failure as
        visible partial-drain state. Returns True when clean."""
        handle.drain_error = None
        try:
            handle.engine.begin_drain()
            return True
        except Exception as e:
            handle.drain_error = str(e)[:500]
            self._m_drain_errors.inc()
            self._events.emit(
                "router_drain_error", severity="critical",
                replica=handle.replica_id, error=str(e)[:200])
            log.error(f"replica {handle.replica_id} drain error: {e}")
            return False

    def drain_replica(self, replica_id: str) -> dict[str, Any]:
        """Coordinated single-replica drain (rolling restart): stop
        placement here, let in-flight streams finish, and MIGRATE idle
        sessions' parked KV to a healthy replica so their next turn
        restores there instead of re-prefilling (docs/ROUTER.md). When
        a session has no parked entry, migration is off, the policy
        prices prefill cheaper, or the transfer fails/hangs, the old
        behaviour is the fallback: the entry is released and the pin
        dropped — the next turn places fresh and re-prefills. Sessions
        with a stream still running here keep their pin until it
        completes.

        Returns a summary dict; raises KeyError for an unknown id."""
        handle = self._handle(replica_id)
        handle.draining = True
        self._drain_engine(handle)
        busy_sessions = {sid for sid, h
                         in list(self._routes.values())
                         if h is handle}
        moved = self.affinity.drop_replica(replica_id,
                                           keep=busy_sessions)
        self.policy.drop_replica(replica_id)
        migrated_kv = released = 0
        channel_wedged = False
        for sid in moved:
            dst = None if channel_wedged \
                else self._migrate_target(sid, handle)
            if dst is not None:
                status = self._migrate_session(sid, handle, dst)
                if status == "ok":
                    # The entry now lives on dst: re-pin the session
                    # there so its next turn goes straight to its
                    # restored KV — UNLESS a new turn already placed
                    # it somewhere during the transfer window (that
                    # replica holds fresher KV than what just moved;
                    # the migrated copy ages out by TTL/LRU).
                    if self.affinity.get(sid) is None:
                        self.affinity.set(sid, dst.replica_id)
                    migrated_kv += 1
                    continue
                if status == "timeout":
                    # One hung transfer means the channel (NIC, peer)
                    # is wedged: N sessions must not each pay the
                    # full timeout — the drain stays bounded by ONE
                    # timeout and the rest release immediately.
                    channel_wedged = True
            # Fallback: purge the parked KV on the draining replica
            # (keeping the entry would only pin host RAM on a replica
            # that is going away); the next turn re-prefills elsewhere.
            try:
                handle.engine.release_session(sid)
            except Exception:
                pass
            released += 1
        self._events.emit("router_drain", severity="warning",
                          scope="replica", replica=replica_id,
                          migrated_sessions=len(moved),
                          migrated_kv=migrated_kv, released=released,
                          busy_sessions=len(busy_sessions),
                          drain_error=handle.drain_error)
        self._update_gauges()
        return {"replica_id": replica_id, "draining": True,
                "migrated_sessions": len(moved),
                "migrated_kv": migrated_kv, "released": released,
                "drain_error": handle.drain_error,
                "busy_sessions": sorted(busy_sessions)}

    # ---------------- cross-replica KV migration ----------------

    def _migrate_target(self, session_id: str,
                        src: ReplicaHandle) -> ReplicaHandle | None:
        """Pick where a parked session's KV should go — or None when
        migration is off, nothing is parked, or the three-way policy
        prices re-prefill cheaper than the transfer. Least-loaded
        available replica wins (no affinity side effects here)."""
        if not self.migrate_enabled:
            return None
        if not self._migration_priced(session_id, src):
            return None
        # Never migrate a session's KV onto a prefill-role replica:
        # its next decode turn could not be served there (the engine's
        # role gate rejects decode streams), so the entry would just
        # age out unreachable.
        candidates = [h for h in self.replicas
                      if h is not src and h.available()
                      and role_of(h) != ROLE_PREFILL]
        if not candidates:
            return None
        return min(candidates, key=lambda h: h.load_score())

    def _migration_priced(self, session_id: str,
                          src: ReplicaHandle) -> bool:
        """True when ``src`` holds a parked entry for the session AND
        the three-way policy prices moving it cheaper than
        re-prefilling — the single gate both drain and failover
        migration run."""
        try:
            info = src.parked_info(session_id)
        except Exception:
            return False
        if info is None:
            return False
        kept, nbytes = info
        return self.kv_policy.decide(kept, nbytes, local=False,
                                     migratable=True) == "migrate"

    def _migrate_session(self, session_id: str, src: ReplicaHandle,
                         dst: ReplicaHandle,
                         request_id: str = "") -> str:
        """One bounded migration: run the transfer on a disposable
        worker thread so a hung channel (router.migrate_send=hang, a
        wedged NIC) can NEVER wedge the caller — drain and failover
        wait at most ``migrate_timeout_s`` and fall back to
        re-prefill. On success the source entry is dropped (its bytes
        leave that pool exactly); on any failure both pools are
        untouched by construction (transfer() exports a peek and the
        target's put is atomic; a worker that outlives the deadline
        undoes its own late import). Returns ``"ok"``, ``"failed"``,
        or ``"timeout"`` — drain treats a timeout as the channel being
        wedged and stops attempting further migrations."""
        t0 = self._clock()
        done = threading.Event()
        abandoned = threading.Event()
        handoff = threading.Lock()
        box: dict[str, Any] = {}
        # Captured HERE: the ContextVar carrying the fleet trace id is
        # copied into asyncio.to_thread contexts but NOT into the plain
        # worker thread below — the wire header (and the span plumbing)
        # must travel explicitly.
        traceparent = current_traceparent()
        tracer = self._tracer if request_id else None

        def work() -> None:
            try:
                result = _migrate.transfer(src, dst, session_id,
                                           traceparent=traceparent,
                                           tracer=tracer,
                                           request_id=request_id)
            except BaseException as e:  # disposable thread: report all
                result = (False, 0, str(e), 0)
            with handoff:
                if not abandoned.is_set():
                    box["result"] = result
                    done.set()
                    return
            # The caller already timed out and fell back to re-prefill
            # (drain may have released the source entry, failover
            # re-prefilled). If the slow transfer then LANDED, the
            # entry would exist on the target with nobody owning it —
            # undo the import so exact-accounting holds even for a
            # worker that outlives its deadline. Guarded: the session
            # may have parked a FRESH entry on the target since (the
            # resumed turn completed there) — only drop when the pool
            # still holds what THIS transfer imported (same kept);
            # otherwise leave it (an orphan ages out by TTL/LRU, a
            # destroyed fresh entry costs the session a full
            # re-prefill).
            if result[0]:
                try:
                    info = dst.parked_info(session_id)
                    if info is not None and info[0] == result[3]:
                        dst.drop_parked(session_id)
                except Exception:
                    pass

        threading.Thread(target=work, daemon=True,
                         name="router-migrate").start()
        timed_out = not done.wait(self.migrate_timeout_s)
        if timed_out:
            # Atomic handoff: either the worker already posted its
            # result (use it), or it is now marked abandoned and will
            # undo a late success itself.
            with handoff:
                if "result" not in box:
                    abandoned.set()
                else:
                    timed_out = False
        if timed_out:
            self._m_migration_failures.inc()
            self._events.emit(
                "router_migration_failed", severity="warning",
                session=session_id, src=src.replica_id,
                dst=dst.replica_id, reason="timeout",
                timeout_s=self.migrate_timeout_s)
            log.warning(f"KV migration {src.replica_id} -> "
                        f"{dst.replica_id} for {session_id} timed out "
                        f"after {self.migrate_timeout_s}s; falling "
                        "back to re-prefill")
            return "timeout"
        ok, nbytes, reason = (box.get("result")
                              or (False, 0, "worker died", 0))[:3]
        if not ok:
            self._m_migration_failures.inc()
            self._events.emit(
                "router_migration_failed", severity="warning",
                session=session_id, src=src.replica_id,
                dst=dst.replica_id, reason=str(reason)[:200])
            log.warning(f"KV migration {src.replica_id} -> "
                        f"{dst.replica_id} for {session_id} failed: "
                        f"{reason}")
            return "failed"
        dt = max(self._clock() - t0, 1e-6)
        # Target confirmed: NOW the source gives its copy up (exact
        # byte accounting — the entry was owned by exactly one pool at
        # every instant an observer could look).
        try:
            src.drop_parked(session_id)
        except Exception:
            pass  # a dead source's pool entry dies with the replica
        self._m_migrations.inc()
        self._m_migration_bytes.inc(nbytes)
        self._m_migration_ms.observe(dt * 1000.0)
        self.kv_policy.note_migrate(nbytes, dt)
        self._events.emit("router_migration", severity="info",
                          session=session_id, src=src.replica_id,
                          dst=dst.replica_id, bytes=nbytes,
                          ms=round(dt * 1000.0, 2))
        log.info(f"migrated {nbytes} parked-KV bytes for {session_id}: "
                 f"{src.replica_id} -> {dst.replica_id} in "
                 f"{dt * 1000:.1f} ms")
        return "ok"

    def pending_requests(self) -> int:
        return sum(self._safe(h, "pending_requests", 0)
                   for h in self.replicas)

    # ---------------- elastic membership (router/elastic.py) -------

    def add_replica(self, handle: ReplicaHandle) -> None:
        """Register a freshly built replica (scale-up). The list is
        REBOUND, never mutated in place — every reader (placement,
        probe loop, failover) sees either the old or the new list."""
        if any(h.replica_id == handle.replica_id for h in self.replicas):
            raise ValueError(f"duplicate replica id "
                             f"{handle.replica_id!r}")
        self.replicas = self.replicas + [handle]
        self._m_replicas.set(len(self.replicas))
        self._update_gauges()

    def remove_replica(self, replica_id: str) -> ReplicaHandle:
        """Deregister a replica (scale-down, after its drain-then-
        migrate emptied it). The caller owns shutting the engine down.
        Raises KeyError for an unknown id."""
        handle = self._handle(replica_id)
        if len(self.replicas) <= 1:
            raise ValueError("cannot remove the last replica")
        self.replicas = [h for h in self.replicas if h is not handle]
        self.affinity.drop_replica(replica_id)
        self.policy.drop_replica(replica_id)
        self._m_replicas.set(len(self.replicas))
        self._update_gauges()
        return handle

    # ---------------- probing ----------------

    def _probe_loop(self) -> None:
        while not self._probe_stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception as e:  # the probe loop must never die
                log.error(f"router probe failed: {e}", exc_info=True)

    def probe_once(self) -> None:
        """Probe every replica once and refresh gauges/affinity.
        Public and synchronous so tests drive health transitions
        deterministically without the probe thread."""
        for h in self.replicas:
            before = h.state
            slo_before = h.last_probe.get("slo_alert", "ok")
            t_probe = time.monotonic()
            h.probe_now()
            if self._tracer.enabled:
                # Process-level probe row: the fleet's health sampling
                # is visible in the same trace dump as engine steps.
                self._tracer.step(
                    "probe", t_probe, time.monotonic(),
                    replica=h.replica_id, state=h.state)
            if h.last_probe.get("slo_alert", "ok") == "page" \
                    and slo_before != "page":
                # A remote replica's own SLO engine crossed into page
                # (its /health body said so). One event per transition:
                # the fleet flight recorder fans out evidence
                # collection while the incident is still live.
                self._events.emit(
                    "replica_slo_page", severity="critical",
                    replica=h.replica_id,
                    slo=h.last_probe.get("slo_alert"))
            if h.state != before:
                self._events.emit(
                    "router_replica_dead" if h.state == STATE_DEAD
                    else "router_replica_state",
                    severity=("critical" if h.state == STATE_DEAD
                              else "info"),
                    replica=h.replica_id, was=before, now=h.state)
                if h.state == STATE_DEAD:
                    # Idle sessions pinned to a dead replica re-place
                    # fresh; sessions with live streams are already in
                    # the failover path.
                    busy = {sid for sid, hh
                            in list(self._routes.values())
                            if hh is h}
                    pinned = self.affinity.drop_replica(h.replica_id,
                                                        keep=busy)
                    self.policy.drop_replica(h.replica_id)
                    if h.dead_reason == "probe":
                        # Death by consecutive probe failures is the
                        # network-partition signature (the backend may
                        # be fine — the router just cannot reach it).
                        # The event triggers the flight recorder: the
                        # evidence of WHY the fleet shrank is gone
                        # minutes later.
                        self._m_partitions.inc()
                        self._events.emit(
                            "router_partition", severity="critical",
                            replica=h.replica_id,
                            dead_probes=h.dead_probes,
                            pinned_sessions=len(pinned),
                            busy_streams=len(busy))
        self.affinity.prune()
        self._update_gauges()

    def _update_gauges(self) -> None:
        self._m_available.set(
            sum(1 for h in self.replicas if h.available()))

    # ---------------- routing ----------------

    def _handle(self, replica_id: str) -> ReplicaHandle:
        for h in self.replicas:
            if h.replica_id == replica_id:
                return h
        raise KeyError(f"unknown replica {replica_id!r}")

    def _place(self, session_id: str, exclude: set[str],
               prefix_key: str | None = None,
               roles: tuple[str, ...] | None = None) -> ReplicaHandle:
        handle, affine = self.policy.place(session_id, self.replicas,
                                           exclude,
                                           prefix_key=prefix_key,
                                           roles=roles)
        if handle is None:
            self._m_sheds.inc()
            raise AdmissionRejected(
                "no healthy replica available"
                + (" (fleet is draining)" if self._draining else ""),
                retry_after=max(1.0, self.probe_interval_s or 1.0),
                reason="no_replica")
        self._m_placements.inc()
        if affine:
            self._m_affinity_hits.inc()
        return handle

    # Chained-digest parameters mirroring the engine-side radix
    # prefix cache (kvcache/radix.py): fixed char blocks, each link
    # committing to everything before it, capped at a small depth.
    # ~1 KB of leading content covers the system prompt + few-shot
    # header in practice and is STABLE as a transcript grows, so
    # every turn of an agent loop maps to the same key.
    _PREFIX_CHAIN_CHARS = 256
    _PREFIX_CHAIN_DEPTH = 4

    @classmethod
    def _prefix_key(cls, messages: list[dict]) -> str | None:
        """Radix chain-hash prefix of the request's leading history
        (every message before the final turn, system prompt included):
        chained sha1 over fixed char blocks, the same chaining scheme
        the engine's radix tree uses over token blocks, so requests
        sharing it co-locate onto the replica most likely to already
        hold their cached prefix blocks. Upgrades the old system-
        prompt-only sha1: multi-turn transcripts without a system
        message now co-locate too. None when there is no leading
        content — bare single-turn traffic spreads least-loaded as
        before."""
        head = messages[:-1] if messages else []
        text = "".join(
            f"{m.get('role', '')}\x1f{m.get('content') or ''}\x1e"
            for m in head)
        if not text.strip("\x1f\x1e"):
            return None
        digest = ""
        for i in range(cls._PREFIX_CHAIN_DEPTH):
            chunk = text[i * cls._PREFIX_CHAIN_CHARS:
                         (i + 1) * cls._PREFIX_CHAIN_CHARS]
            if not chunk:
                break
            digest = chain_digest(digest,
                                  chunk.encode("utf-8", "replace"))
        return digest[:16]

    def _failover_migrate(self, session_id: str, src: ReplicaHandle,
                          dst: ReplicaHandle,
                          request_id: str = "") -> bool:
        """Best-effort parked-KV pull from the failed replica to the
        chosen survivor (migrate worker thread via to_thread). Never
        raises. Runs under the caller's copied context (to_thread), so
        the fleet trace id is still bound here — it is captured into an
        explicit traceparent before the plain worker thread loses it."""
        try:
            if not self._migration_priced(session_id, src):
                return False
            return self._migrate_session(
                session_id, src, dst, request_id=request_id) == "ok"
        except Exception as e:
            log.debug(f"failover migration probe failed for "
                      f"{session_id}: {e}")
            return False

    # ---------------- disaggregated prefill/decode (router/disagg.py,
    # docs/ROUTER.md "Disaggregated prefill/decode") ----------------

    def _decode_roles(self) -> tuple[str, ...] | None:
        """Role filter for normal (decode) stream placement: None in
        an all-mixed fleet (today's behaviour, zero role checks on the
        hot path), the decode/mixed tier once any replica carries a
        role — a decode stream must never land on a prefill replica,
        whose engine rejects it."""
        if all(role_of(h) == ROLE_MIXED for h in self.replicas):
            return None
        return DECODE_ROLES

    def _estimate_prompt_tokens(self, messages: list[dict],
                                params: GenerationParams) -> int:
        """Prompt length for the threshold routing decision. Exact
        when an in-proc replica lends its tokenizer; chars/4 for
        all-remote fleets — the threshold gates a heuristic either
        way, and the engine re-counts authoritatively at admission."""
        if self._tok is False:
            self._tok = next(
                (t for h in self.replicas
                 if (t := getattr(h.engine, "tokenizer", None))
                 is not None), None)
        if self._tok is not None:
            try:
                if params.raw_prompt:
                    return len(self._tok.encode_prompt(
                        raw_prompt_text(messages)))
                return len(self._tok.apply_chat_template(messages))
            except Exception:
                pass
        chars = sum(len(str(m.get("content") or ""))
                    for m in messages)
        return max(1, chars // 4)

    @staticmethod
    def _safe_parked_info(src: ReplicaHandle,
                          session_id: str) -> tuple[int, int] | None:
        try:
            return src.parked_info(session_id)
        except Exception:
            return None

    async def _disagg_settle(self, request_id: str, session_id: str,
                             src: ReplicaHandle,
                             prefix_key: str | None,
                             ) -> tuple[ReplicaHandle, int, int]:
        """Post-prefill half of a handoff: wait for the async park
        (the D2H fetch lands on the source's offload thread), pick the
        decode replica (radix prefix affinity applies WITHIN the
        decode tier), and move the entry over the migration wire.
        Unbounded by itself — the caller wraps the whole settle in ONE
        ``migrate_timeout_s`` budget, so a hung park, a hung channel
        or the ``router.handoff`` chaos hang all cost at most one
        timeout before the fallback."""
        if _fp.enabled:
            # Chaos seam: the handoff settling — fire_ASYNC (event
            # loop) so delay/hang rules yield instead of freezing
            # every stream; `error` here is a handoff channel fault
            # and must fall back to mixed placement with zero
            # client-visible error frames.
            await _fp.fire_async("router.handoff",
                                 session_id=session_id,
                                 replica=src.replica_id)
        while True:
            info = await asyncio.to_thread(self._safe_parked_info,
                                           src, session_id)
            if info is not None:
                break
            await asyncio.sleep(0.005)
        kept, nbytes = info
        dst, _ = self.policy.place(session_id, self.replicas,
                                   {src.replica_id},
                                   prefix_key=prefix_key,
                                   roles=DECODE_ROLES)
        if dst is None:
            raise LLMServiceError("no decode replica for handoff",
                                  category=ErrorCategory.CONNECTION,
                                  recoverable=True)
        # No pricing re-check here: the transfer was priced on the
        # estimate BEFORE the prefill ran; with the prefill paid, the
        # transfer is the cheap way to finish the job.
        status = await asyncio.to_thread(self._migrate_session,
                                         session_id, src, dst,
                                         request_id)
        if status != "ok":
            raise LLMServiceError(f"handoff transfer {status}",
                                  category=ErrorCategory.CONNECTION,
                                  recoverable=True)
        return dst, kept, nbytes

    async def _disagg_handoff(self, request_id: str, session_id: str,
                              messages: list[dict],
                              params: GenerationParams,
                              prefix_key: str | None,
                              ) -> ReplicaHandle | None:
        """The prefill→handoff→decode lifecycle, client-invisibly: run
        a ``prefill_only`` sub-request on the prefill tier, then (one
        ``migrate_timeout_s`` budget) wait for the parked entry and
        migrate it to a decode replica, which is returned with the
        session pinned to it — the caller's normal placement hits the
        pin and the stream admits via the restore path. Any failure on
        either side returns None: the caller places decode-local and
        re-prefills, so the client sees no error frame, ever."""
        src = self.policy.pick_tier(self.replicas, (ROLE_PREFILL,))
        if src is None:
            self._m_handoff_fallbacks.inc()
            self.disagg.note_fallback()
            return None
        rid = f"{request_id}.prefill"
        t0 = time.monotonic()
        ok = False
        failure = ""
        pf_stats: dict[str, Any] = {}
        self._handoff_streams[request_id] = (src, rid)
        src.inflight.add(rid)
        src.placements += 1
        try:
            async for ev in src.engine.generate(
                    request_id=rid, session_id=session_id,
                    messages=messages,
                    params=_dc_replace(params, prefill_only=True)):
                et = ev.get("type")
                if et == "done":
                    ok = True
                    pf_stats = ev.get("stats") or {}
                elif et in ("error", "cancelled"):
                    failure = str(ev.get("error", et))
        except asyncio.CancelledError:
            src.engine.cancel(rid)
            raise
        except Exception as e:
            failure = str(e)
        finally:
            src.inflight.discard(rid)
            self._handoff_streams.pop(request_id, None)
        if request_id in self._cancelled:
            return None  # the caller's loop emits the cancelled frame
        if ok:
            st = pf_stats
            if st.get("ttft_ms") and st.get("prefill_tokens"):
                # The prefill tier's completions feed the SAME prefill
                # EMA as decode-tier streams: prefill_only TTFT is the
                # chunked prefill wall time, the honest throughput the
                # handoff pricing needs.
                self.kv_policy.note_prefill(
                    int(st["prefill_tokens"]),
                    float(st["ttft_ms"]) / 1000.0)
            t_settle = time.monotonic()
            try:
                dst, kept, nbytes = await asyncio.wait_for(
                    self._disagg_settle(request_id, session_id, src,
                                        prefix_key),
                    timeout=self.migrate_timeout_s)
                dt_ms = (time.monotonic() - t_settle) * 1000.0
                self._m_handoffs.inc()
                self._m_handoff_ms.observe(dt_ms)
                self.disagg.note_handoff(kept, nbytes)
                if self._tracer.enabled:
                    self._tracer.add_span(
                        request_id, "handoff", t0, time.monotonic(),
                        src=src.replica_id, dst=dst.replica_id,
                        kept=kept, bytes=nbytes,
                        settle_ms=round(dt_ms, 2))
                self._events.emit(
                    "router_disagg_handoff", severity="info",
                    session=session_id, src=src.replica_id,
                    dst=dst.replica_id, kept=kept, bytes=nbytes,
                    settle_ms=round(dt_ms, 2))
                return dst
            except asyncio.TimeoutError:
                failure = (f"handoff settle exceeded "
                           f"{self.migrate_timeout_s}s")
            except Exception as e:  # incl. FaultInjected from the seam
                failure = str(e)
        # ---------- fallback to mixed placement ----------
        # The prefill leg died mid-chunk, the settle hung, or the
        # transfer failed: the decode tier re-prefills the prompt —
        # slower, never wrong, and the client sees nothing. A stale
        # parked entry left on the prefill replica ages out by
        # TTL/LRU; the pin (if the settle's place() set one) must not
        # survive, or the next turn would chase KV that never arrived.
        self._m_handoff_fallbacks.inc()
        self.disagg.note_fallback()
        self.affinity.drop(session_id)
        if self._tracer.enabled:
            self._tracer.add_span(request_id, "handoff", t0,
                                  time.monotonic(),
                                  src=src.replica_id, ok=False,
                                  error=failure[:200])
        self._events.emit("router_disagg_fallback", severity="warning",
                          session=session_id, src=src.replica_id,
                          error=failure[:200])
        log.warning(f"[{request_id}] disagg handoff via "
                    f"{src.replica_id} fell back to mixed placement: "
                    f"{failure}")
        return None

    async def generate(self, request_id: str, session_id: str,
                       messages: list[dict], params: GenerationParams,
                       ) -> AsyncGenerator[dict, None]:
        if self._draining:
            self._m_sheds.inc()
            raise AdmissionRejected(
                "fleet is draining: finishing in-flight requests, not "
                "accepting new ones", retry_after=5.0, reason="draining")
        excluded: set[str] = set()
        delivered = 0          # chars already yielded to the caller
        progress_mark = 0      # delivered at the last failure
        attempt = 0
        resumed_total = 0
        pending_resume = False
        prefix_key = self._prefix_key(messages)
        failed_handle: ReplicaHandle | None = None
        roles = self._decode_roles()
        try:
            if roles is not None and self.migrate_enabled \
                    and params.structured is None \
                    and any(role_of(h) == ROLE_PREFILL
                            and h.available()
                            for h in self.replicas) \
                    and self.disagg.wants_handoff(
                        self._estimate_prompt_tokens(messages, params)):
                # Disaggregated path: long prompt → prefill tier, KV
                # over the wire, session pinned to the decode replica.
                # Success or fallback, the loop below runs unchanged —
                # on success the pin routes it to the decode replica
                # where the restore path admits; on fallback it places
                # decode-local and re-prefills (no error frame either
                # way).
                await self._disagg_handoff(request_id, session_id,
                                           messages, params,
                                           prefix_key)
            while True:
                # A cancel can land while no replica owns the stream —
                # between attempts, or while the generator is suspended
                # yielding the resumed frame. Check at every point we
                # regain control with no replica-side stream to carry
                # the cancel for us.
                if request_id in self._cancelled:
                    yield {"type": "cancelled",
                           "finish_reason": "cancelled", "stats": {}}
                    return
                if _fp.enabled:
                    try:
                        # Chaos seam: a placement fault is what a fully
                        # partitioned fleet looks like — it must
                        # surface as a shed with retry_after
                        # (rate-limit taxonomy, breaker untouched),
                        # never an internal error. fire_ASYNC: this
                        # runs on the event loop, so delay/hang rules
                        # must yield instead of freezing every stream
                        # and the /debug/fault clear path.
                        await _fp.fire_async("router.place",
                                             session_id=session_id)
                    except _fp.FaultInjected as e:
                        self._m_sheds.inc()
                        raise AdmissionRejected(
                            f"placement failed: {e}",
                            retry_after=max(1.0,
                                            self.probe_interval_s
                                            or 1.0),
                            reason="no_replica") from e
                t_place = time.monotonic()
                handle = self._place(session_id, excluded, prefix_key,
                                     roles=roles)
                if self._tracer.enabled:
                    self._tracer.add_span(
                        request_id, "place", t_place, time.monotonic(),
                        replica=handle.replica_id, attempt=attempt,
                        excluded=len(excluded))
                if failed_handle is not None \
                        and failed_handle is not handle:
                    # Failover migration: the dead/failed replica may
                    # still hold this session's parked KV (an in-proc
                    # pool survives its engine thread; a drained
                    # remote still answers /kv). Pulling it to the
                    # survivor BEFORE re-dispatching turns the resume's
                    # transcript re-prefill into a restore + delta
                    # prefill. Bounded by migrate_timeout_s and fully
                    # best-effort — a failure changes nothing.
                    src = failed_handle
                    failed_handle = None
                    if self.migrate_enabled:
                        await asyncio.to_thread(
                            self._failover_migrate, session_id, src,
                            handle, request_id)
                if pending_resume:
                    pending_resume = False
                    resumed_total += 1
                    self._m_resumes.inc()
                    # The stitched-trace resume marker (stitch.py
                    # RESUME_SPAN): exactly one per failover the client
                    # survived, tagged with where the stream landed.
                    self._tracer.event(request_id, "resume",
                                       replica=handle.replica_id,
                                       attempt=attempt)
                    yield {"type": "resumed",
                           "replica": handle.replica_id,
                           "attempt": attempt}
                    if request_id in self._cancelled:
                        yield {"type": "cancelled",
                               "finish_reason": "cancelled",
                               "stats": {}}
                        return
                self._routes[request_id] = (session_id, handle)
                handle.inflight.add(request_id)
                handle.placements += 1
                failure: str | None = None
                skip = delivered
                t0 = self._clock()
                try:
                    async for ev in handle.engine.generate(
                            request_id, session_id, messages, params):
                        et = ev.get("type")
                        if et == "token":
                            text = ev.get("text", "")
                            if skip > 0:  # resume overlap trim
                                if len(text) <= skip:
                                    skip -= len(text)
                                    continue
                                text = text[skip:]
                                skip = 0
                            if not text:
                                continue
                            delivered += len(text)
                            yield {**ev, "text": text}
                        elif et in ("done", "cancelled"):
                            st = ev.get("stats") or {}
                            if et == "done" and st.get("ttft_ms") \
                                    and st.get("prefill_tokens"):
                                # Feed the three-way policy's prefill
                                # EMA from the fleet's own completions
                                # — tokens actually PREFILLED over
                                # TTFT, so the migrate-vs-reprefill
                                # pricing tracks real hardware. NOT
                                # prompt_tokens: a cache-hit turn
                                # prefills only the delta, and pricing
                                # with the full prompt would inflate
                                # the EMA by the hit fraction and turn
                                # migration off exactly in the warm
                                # steady state it serves. Engines that
                                # don't report the field (remote,
                                # fakes) just don't feed the EMA.
                                self.kv_policy.note_prefill(
                                    int(st["prefill_tokens"]),
                                    float(st["ttft_ms"]) / 1000.0)
                            if resumed_total:
                                ev = {**ev,
                                      "stats": {**st,
                                                "resumed": resumed_total}}
                            yield ev
                            return
                        elif et == "error":
                            # code "internal_error" is emitted ONLY by
                            # the engine's crash/shutdown abort path
                            # (_abort_all) — a replica fault even when
                            # check_connection() hasn't flipped yet
                            # (the abort events race the thread's
                            # teardown). Anything else is judged by
                            # liveness: deadline_expired / stalled /
                            # validation errors from a live replica
                            # propagate.
                            if ev.get("code") == "internal_error" \
                                    or not handle.alive():
                                failure = str(ev.get("error", ""))
                                break
                            yield ev  # genuine request error: propagate
                            return
                        else:
                            yield ev  # tool_call etc.: pass through
                except asyncio.CancelledError:
                    handle.engine.cancel(request_id)
                    raise
                except AdmissionRejected:
                    # This replica's queue shed us. A fresh request can
                    # try a less-loaded replica; a resumed stream (or a
                    # fully-excluded fleet) must surface the shed with
                    # its retry_after.
                    excluded.add(handle.replica_id)
                    if delivered == 0 and len(excluded) < len(
                            self.replicas):
                        continue
                    raise
                except LLMServiceError as e:
                    if e.category in _FAULT_CATEGORIES \
                            or not handle.alive():
                        failure = str(e)
                    else:
                        raise
                except Exception as e:
                    if not handle.alive():
                        failure = str(e)
                    else:
                        raise
                finally:
                    handle.inflight.discard(request_id)
                if failure is None:
                    # Stream ended with no terminal event (a replica
                    # torn down mid-yield can do this): same treatment
                    # as an explicit failure.
                    failure = "stream ended without a terminal event"
                # ---------- failover ----------
                died = handle.note_stream_failure()
                self._m_failovers.inc()
                self._tracer.event(request_id, "failover")
                self._events.emit(
                    "router_failover", severity="critical",
                    replica=handle.replica_id, request=request_id,
                    session=session_id, mid_stream=delivered > 0,
                    attempt=attempt, error=failure[:200])
                if died:
                    busy = {sid for sid, hh
                            in list(self._routes.values())
                            if hh is handle}
                    self.affinity.drop_replica(handle.replica_id,
                                               keep=busy)
                    self.policy.drop_replica(handle.replica_id)
                failed_handle = handle
                self._update_gauges()
                log.warning(
                    f"[{request_id}] replica {handle.replica_id} failed "
                    f"{'mid-stream' if delivered else 'pre-token'} "
                    f"(attempt {attempt}): {failure}")
                if request_id in self._cancelled:
                    yield {"type": "cancelled",
                           "finish_reason": "cancelled", "stats": {}}
                    return
                if delivered > progress_mark:
                    # The stream made progress since its last failure,
                    # so earlier exclusions (and spent retries) are
                    # stale: during a rolling restart every replica
                    # fails ONCE but is healthy again by the time a
                    # long-lived stream comes back around —
                    # accumulating them forever would shed a stream
                    # that merely outlives N sequential restarts. Only
                    # the replica that JUST failed is suspect;
                    # back-to-back failures with no progress still
                    # accumulate (no ping-pong between two dying
                    # replicas, and the retry budget still bounds
                    # them).
                    excluded.clear()
                    attempt = 0
                progress_mark = delivered
                excluded.add(handle.replica_id)
                attempt += 1
                if attempt > self.failover_retries:
                    yield {"type": "error",
                           "error": f"replica {handle.replica_id} "
                           f"failed and failover retries exhausted: "
                           f"{failure}",
                           "code": "replica_failed"}
                    return
                if delivered > 0:
                    if params.structured is not None:
                        # Constrained stream (docs/STRUCTURED.md): a
                        # resume re-generates the document on the
                        # survivor, and splicing a NEW document onto
                        # already-delivered text would hand the client
                        # invalid output — the one thing structured
                        # mode promises never happens. Fail the stream
                        # instead; pre-first-token failover above
                        # still re-routes silently.
                        yield {"type": "error",
                               "error": f"replica {handle.replica_id} "
                               "died mid-stream of a structured "
                               "generation (resume would break the "
                               f"validity contract): {failure}",
                               "code": "replica_failed"}
                        return
                    if not self.resume_enabled:
                        yield {"type": "error",
                               "error": f"replica {handle.replica_id} "
                               f"died mid-stream (resume disabled): "
                               f"{failure}",
                               "code": "replica_failed"}
                        return
                    # Affinity moves with the resume: the survivor
                    # re-prefills the transcript and becomes the
                    # session's home.
                    pending_resume = True
                self._tracer.add_span(request_id, "failover", t0,
                                      self._clock(),
                                      replica=handle.replica_id,
                                      mid_stream=delivered > 0)
        finally:
            self._routes.pop(request_id, None)
            self._cancelled.discard(request_id)

    # ---------------- EngineBase surface ----------------

    def cancel(self, request_id: str) -> bool:
        # Mark first: a cancel landing between failover attempts (no
        # replica owns the stream at that instant) must still terminate
        # the retry loop.
        self._cancelled.add(request_id)
        # A cancel during a disagg handoff's prefill leg: the client's
        # request_id never reaches the prefill replica (the sub-request
        # runs as "<id>.prefill"), so forward the cancel there — the
        # handoff aborts, and the outer loop emits the cancelled frame.
        handoff = self._handoff_streams.get(request_id)
        if handoff is not None:
            src, rid = handoff
            try:
                src.engine.cancel(rid)
            except Exception:
                pass
        route = self._routes.get(request_id)
        if route is not None:
            try:
                return bool(route[1].engine.cancel(request_id))
            except Exception:
                return False
        return False

    def release_session(self, session_id: str) -> None:
        self.affinity.drop(session_id)
        # Fan out: a failed-over session may have parked KV on more
        # than one replica (release is idempotent everywhere).
        for h in self.replicas:
            try:
                h.engine.release_session(session_id)
            except Exception:
                pass

    def check_connection(self) -> bool:
        return self._started and any(h.available() and h.alive()
                                     for h in self.replicas)

    def get_model_info(self) -> dict:
        info: dict[str, Any] = {}
        for h in self.replicas:
            try:
                info = dict(h.engine.get_model_info())
                break
            except Exception:
                continue
        info["fleet_size"] = len(self.replicas)
        info["router"] = True
        return info

    def get_stats(self) -> dict:
        per_replica = {}
        waiting = running = 0
        for h in self.replicas:
            stats = self._safe(h, "get_stats", {}) or {}
            per_replica[h.replica_id] = {
                "state": h.state, "draining": h.draining,
                "role": role_of(h),
                "inflight": len(h.inflight),
                "waiting": stats.get("waiting", 0),
            }
            waiting += int(stats.get("waiting", 0) or 0)
            running += int(stats.get("running", 0) or 0)
        return {
            "router": {
                "replicas": len(self.replicas),
                "available": sum(1 for h in self.replicas
                                 if h.available()),
                "dead": sum(1 for h in self.replicas
                            if h.state == STATE_DEAD),
                "affinity_sessions": len(self.affinity),
                "placements": self._m_placements.value,
                "affinity_hits": self._m_affinity_hits.value,
                "failovers": self._m_failovers.value,
                "resumes": self._m_resumes.value,
                "sheds": self._m_sheds.value,
                "migrations": self._m_migrations.value,
                "migration_failures": self._m_migration_failures.value,
                "draining": self._draining,
            },
            "per_replica": per_replica,
            "waiting": waiting,
            "running": running,
        }

    def fleet_stats(self) -> dict:
        """The /fleet endpoint's body: registry view with live scores."""
        replicas = []
        for h in self.replicas:
            d = h.to_dict()
            score = h.load_score()
            d["load_score"] = (None if score == float("inf")
                               else round(score, 3))
            replicas.append(d)
        return {
            "replicas": replicas,
            "affinity_sessions": len(self.affinity),
            "draining": self._draining,
            # A drain that failed on some replica is a PARTIAL drain:
            # operators watching /fleet see which handle is stuck
            # (drain_error per replica) instead of a silent log line.
            "partial_drain": any(h.drain_error is not None
                                 for h in self.replicas),
            "migration": {
                "enabled": self.migrate_enabled,
                "timeout_s": self.migrate_timeout_s,
                "policy": self.kv_policy.stats(),
            },
            # Disaggregated serving view (docs/ROUTER.md): per-role
            # tier aggregates (queue depth and slot occupancy per
            # tier — the elastic scaler's signals) plus the handoff
            # controller's counters and learned wire-cost model.
            "disagg": {
                "tiers": tier_stats(self.replicas),
                **self.disagg.stats(),
            },
            "counters": {
                "placements": self._m_placements.value,
                "affinity_hits": self._m_affinity_hits.value,
                "failovers": self._m_failovers.value,
                "resumes": self._m_resumes.value,
                "sheds": self._m_sheds.value,
                "migrations": self._m_migrations.value,
                "migration_failures": self._m_migration_failures.value,
                "migration_bytes": self._m_migration_bytes.value,
                "drain_errors": self._m_drain_errors.value,
                "partitions": self._m_partitions.value,
                "prefix_colocations": self._m_prefix.value,
            },
        }

    # ---------------- fleet observability (docs/OBSERVABILITY.md
    # "Fleet tracing and the token journey") ----------------
    # All three fan out over synchronous HTTP to remote replicas —
    # callers on an event loop must run them off-loop (the serving and
    # monitoring routes do).

    def stitched_trace(self, request_id: str) -> dict[str, Any] | None:
        """ONE cross-replica timeline for a request: local fragments
        (router + serving + any in-proc replica, all in this process's
        tracer) joined with every remote replica's fragments fetched
        over its serving port. None when nobody remembers the id."""
        from fasttalk_tpu.observability.stitch import (collect_fragments,
                                                       stitch)

        frags = collect_fragments(get_tracer(), request_id,
                                  source="router")
        trace_id = frags[0].get("trace_id", "") if frags else ""
        for h in self.replicas:
            try:
                frags.extend(h.fetch_trace(request_id, trace_id))
            except Exception as e:
                log.debug(f"trace fetch from {h.replica_id} failed "
                          f"for {request_id}: {e}")
        return stitch(frags)

    def fleet_metrics(self) -> str:
        """Label-merged Prometheus exposition across the fleet (export
        merge_prometheus): the local registry — router + serving + any
        in-proc replicas, which share it — as ``replica="router"``,
        each remote replica's /metrics under its own label, histograms
        summed. Unreachable replicas become free comments, never a
        broken scrape."""
        from fasttalk_tpu.observability.export import merge_prometheus

        remotes: dict[str, str | None] = {}
        for h in self.replicas:
            if not hasattr(h, "base_url"):
                continue  # in-proc: already in the local registry
            try:
                remotes[h.replica_id] = h.fetch_metrics()
            except Exception:
                remotes[h.replica_id] = None
        return merge_prometheus(get_metrics().prometheus(), "router",
                                remotes)

    def fleet_slo(self) -> dict[str, Any]:
        """Fleet SLO rollup: the local engine's report (shared by the
        router front and in-proc replicas) plus each remote replica's
        /slo, with the worst alert across the fleet on top."""
        from fasttalk_tpu.observability.slo import get_slo

        rank = ("ok", "warn", "page").index
        engine = get_slo()
        local = engine.snapshot()
        worst = max(list(engine.alert_summary().values()) or ["ok"],
                    key=lambda s: rank(s) if s in ("ok", "warn",
                                                   "page") else 0)
        replicas: dict[str, Any] = {}
        for h in self.replicas:
            if hasattr(h, "base_url"):
                try:
                    report = h.fetch_slo()
                except Exception:
                    report = None
                alert = h.last_probe.get("slo_alert", "ok")
                replicas[h.replica_id] = {"alert": alert,
                                          "report": report}
                if alert in ("warn", "page") \
                        and rank(alert) > rank(worst):
                    worst = alert
            else:
                replicas[h.replica_id] = {"shared_process": True}
        return {"worst_alert": worst, "local": local,
                "replicas": replicas}

    @staticmethod
    def _safe(h: ReplicaHandle, method: str, default):
        try:
            return getattr(h.engine, method)()
        except Exception:
            return default


def build_fleet(cfg) -> FleetRouter:
    """Construct the configured fleet: ``FLEET_REPLICAS`` in-process
    engine replicas (each its own engine instance — CPU fleets for
    test/bench, or dp-style multi-engine on real hardware) plus one
    remote replica per ``ROUTER_BACKENDS`` URL (other FastTalk servers,
    reached through the existing remote.py client protocol)."""
    from dataclasses import replace as dc_replace

    from fasttalk_tpu.engine.factory import build_engine

    inproc_roles = parse_roles(getattr(cfg, "fleet_roles", ""),
                               cfg.fleet_replicas, "FLEET_ROLES")
    handles: list[ReplicaHandle] = []
    for i in range(cfg.fleet_replicas):
        role = inproc_roles[i]
        ecfg = cfg
        if role == ROLE_PREFILL:
            # A prefill-role replica is a batch machine, not a latency
            # machine: deepen its admission queue (long prefills WAIT
            # there, by design — the whole point is that the waiting
            # happens away from decode streams). Slots stay as
            # configured — chunked prefill occupies one slot per
            # request and the engine rejects decode streams by role.
            ecfg = dc_replace(cfg, sched_queue_bound=4
                              * cfg.sched_queue_bound)
        engine = build_engine(ecfg)
        # Component tagging: in-proc replicas share the process tracer,
        # so the replica id on each span is what keeps a stitched
        # trace's fragments attributable (observability/stitch.py).
        engine.set_trace_component(f"inproc-{i}")
        handles.append(ReplicaHandle(
            f"inproc-{i}", engine, role=role,
            dead_probes=cfg.router_dead_probes))
    urls = [u.strip() for u in cfg.router_backends.split(",")
            if u.strip()]
    remote_roles = parse_roles(getattr(cfg, "router_backend_roles", ""),
                               len(urls), "ROUTER_BACKEND_ROLES")
    for i, url in enumerate(urls):
        handle = RemoteReplicaHandle(
            f"remote-{i}", url, cfg.model_name,
            role=remote_roles[i],
            dead_probes=cfg.router_dead_probes,
            timeout_s=cfg.vllm_timeout,
            max_inflight=cfg.remote_max_inflight,
            admission_timeout_s=cfg.sched_default_deadline_s,
            connect_retries=cfg.remote_connect_retries)
        handle.engine.set_trace_component(f"remote-{i}")
        handles.append(handle)
    return FleetRouter(
        handles,
        probe_interval_s=cfg.router_probe_interval_s,
        affinity_ttl_s=cfg.router_affinity_ttl_s,
        failover_retries=cfg.router_failover_retries,
        resume=cfg.router_resume,
        migrate=cfg.router_migrate,
        migrate_timeout_s=cfg.router_migrate_timeout_s,
        prefix_affinity=cfg.router_prefix_affinity,
        disagg_prefill_min_tokens=getattr(
            cfg, "disagg_prefill_min_tokens", 512))
