"""FleetRouter: health-aware routing across N engine replicas.

The scale-OUT tier the ROADMAP north star requires: one EngineBase-
shaped front that spreads sessions across a fleet of engine replicas
(in-process engines and/or remote FastTalk servers), the way
JetStream/llm-d-style deployments front their model servers. Because
the router IS an ``EngineBase``, the entire serving stack — WebSocket
server, OpenAI routes, breaker, drain-on-shutdown — runs unchanged on
top of it; the router slots in where a single engine used to be.

What it adds over a bare engine (docs/ROUTER.md):

- **Replica registry + probes.** A daemon thread probes every replica
  each ``probe_interval_s`` using the signals the stack already
  publishes (check_connection / get_stats for in-proc replicas, the
  /health body for remote ones) — no new health protocol.
- **Session affinity.** A session sticks to the replica holding its
  resident or host-parked KV (policy.py), so the PR-4 restore path
  keeps paying off across the fleet; new sessions place
  weighted-least-loaded (queue depth, overload state, SLO burn).
- **Failover.** A replica dying mid-stream triggers resume-on-survivor:
  the transcript re-prefills on a healthy replica, already-delivered
  text is trimmed from the new stream, and the client sees one
  ``resumed`` event — not an error. Pre-first-token failures re-route
  silently (nothing was delivered, the retry is idempotent); when no
  healthy replica remains the request sheds with ``retry_after``.
- **Coordinated drain.** ``drain_replica()`` stops placement to one
  replica, lets its in-flight streams finish, and migrates its idle
  parked sessions' affinity (their next turn places fresh elsewhere)
  — the fleet keeps serving through a rolling restart.

Resume caveat: the survivor re-generates from the transcript, so with
temperature > 0 the continuation may diverge from what the dead replica
would have said; with greedy sampling it is identical. The overlap trim
is by character count of delivered text.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Any, AsyncGenerator

from fasttalk_tpu.engine.engine import EngineBase, GenerationParams
from fasttalk_tpu.observability.events import get_events
from fasttalk_tpu.observability.trace import get_tracer
from fasttalk_tpu.router.policy import AffinityMap, PlacementPolicy
from fasttalk_tpu.router.replica import (STATE_DEAD, ReplicaHandle,
                                         RemoteReplicaHandle)
from fasttalk_tpu.utils.errors import (AdmissionRejected, ErrorCategory,
                                       LLMServiceError)
from fasttalk_tpu.utils.logger import get_logger
from fasttalk_tpu.utils.metrics import get_metrics

log = get_logger("router")

# LLMServiceError categories that indicate the REPLICA failed (connect
# refused, timeout, OOM) rather than the request being malformed —
# these are failover-eligible; validation/model errors propagate.
_FAULT_CATEGORIES = (ErrorCategory.CONNECTION, ErrorCategory.TIMEOUT,
                     ErrorCategory.RESOURCE)


class FleetRouter(EngineBase):
    """Engine-shaped front over a fleet of replicas."""

    def __init__(self, replicas: list[ReplicaHandle], *,
                 probe_interval_s: float = 2.0,
                 affinity_ttl_s: float = 600.0,
                 failover_retries: int = 2,
                 resume: bool = True,
                 clock=time.monotonic):
        if not replicas:
            raise ValueError("FleetRouter needs at least one replica")
        ids = [h.replica_id for h in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.replicas = list(replicas)
        self.probe_interval_s = probe_interval_s
        self.failover_retries = max(0, failover_retries)
        self.resume_enabled = resume
        self._clock = clock
        self.affinity = AffinityMap(ttl_s=affinity_ttl_s, clock=clock)
        self.policy = PlacementPolicy(self.affinity)
        self._routes: dict[str, tuple[str, ReplicaHandle]] = {}
        self._cancelled: set[str] = set()
        self._draining = False
        self._started = False
        self._probe_thread: threading.Thread | None = None
        self._probe_stop = threading.Event()
        self._events = get_events()
        self._tracer = get_tracer()
        m = get_metrics()
        self._m_replicas = m.gauge(
            "router_replicas", "replicas registered with the router")
        self._m_available = m.gauge(
            "router_replicas_available",
            "replicas currently placeable (not dead, not draining)")
        self._m_placements = m.counter(
            "router_placements_total", "requests placed on a replica")
        self._m_affinity_hits = m.counter(
            "router_affinity_hits_total",
            "placements that reused the session's pinned replica")
        self._m_failovers = m.counter(
            "router_failovers_total",
            "streams that failed on a replica and were re-routed")
        self._m_resumes = m.counter(
            "router_resumes_total",
            "mid-stream failovers resumed on a survivor (client saw a "
            "resumed event, not an error)")
        self._m_sheds = m.counter(
            "router_sheds_total",
            "requests shed by the router (no placeable replica)")
        self._m_replicas.set(len(self.replicas))

    # ---------------- lifecycle ----------------

    def start(self) -> None:
        if self._started:
            return
        self._started = True
        for h in self.replicas:
            try:
                h.engine.start()
            except Exception as e:
                log.error(f"replica {h.replica_id} failed to start: {e}")
        self.probe_once()
        if self.probe_interval_s > 0:
            self._probe_stop.clear()
            self._probe_thread = threading.Thread(
                target=self._probe_loop, name="router-probe", daemon=True)
            self._probe_thread.start()

    def shutdown(self) -> None:
        self._started = False
        self._probe_stop.set()
        if self._probe_thread is not None:
            self._probe_thread.join(timeout=5)
            self._probe_thread = None
        for h in self.replicas:
            try:
                h.engine.shutdown()
            except Exception as e:
                log.error(f"replica {h.replica_id} shutdown error: {e}")

    def warmup(self, level: str = "off") -> None:
        for h in self.replicas:
            h.engine.warmup(level)

    def begin_drain(self) -> None:
        """Fleet-wide drain (server shutdown): every replica stops
        admitting; queued and in-flight work finishes."""
        self._draining = True
        self._events.emit("router_drain", severity="warning",
                          scope="fleet", replicas=len(self.replicas))
        for h in self.replicas:
            h.draining = True
            try:
                h.engine.begin_drain()
            except Exception as e:
                log.error(f"replica {h.replica_id} drain error: {e}")

    def drain_replica(self, replica_id: str) -> dict[str, Any]:
        """Coordinated single-replica drain (rolling restart): stop
        placement here, let in-flight streams finish, and migrate idle
        sessions — their affinity is dropped (next turn places fresh on
        a healthy replica) and their parked KV on this replica is
        released so the pool frees. Sessions with a stream still
        running here keep their pin until it completes.

        Returns a summary dict; raises KeyError for an unknown id."""
        handle = self._handle(replica_id)
        handle.draining = True
        try:
            handle.engine.begin_drain()
        except Exception as e:
            log.error(f"replica {replica_id} drain error: {e}")
        busy_sessions = {sid for sid, h
                         in list(self._routes.values())
                         if h is handle}
        migrated = self.affinity.drop_replica(replica_id,
                                              keep=busy_sessions)
        for sid in migrated:
            # Idle parked sessions: purge their parked KV on the
            # draining replica (their next turn re-prefills elsewhere;
            # keeping the entry would only pin host RAM on a replica
            # that is going away).
            try:
                handle.engine.release_session(sid)
            except Exception:
                pass
        self._events.emit("router_drain", severity="warning",
                          scope="replica", replica=replica_id,
                          migrated_sessions=len(migrated),
                          busy_sessions=len(busy_sessions))
        self._update_gauges()
        return {"replica_id": replica_id, "draining": True,
                "migrated_sessions": len(migrated),
                "busy_sessions": sorted(busy_sessions)}

    def pending_requests(self) -> int:
        return sum(self._safe(h, "pending_requests", 0)
                   for h in self.replicas)

    # ---------------- probing ----------------

    def _probe_loop(self) -> None:
        while not self._probe_stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception as e:  # the probe loop must never die
                log.error(f"router probe failed: {e}", exc_info=True)

    def probe_once(self) -> None:
        """Probe every replica once and refresh gauges/affinity.
        Public and synchronous so tests drive health transitions
        deterministically without the probe thread."""
        for h in self.replicas:
            before = h.state
            h.probe_now()
            if h.state != before:
                self._events.emit(
                    "router_replica_dead" if h.state == STATE_DEAD
                    else "router_replica_state",
                    severity=("critical" if h.state == STATE_DEAD
                              else "info"),
                    replica=h.replica_id, was=before, now=h.state)
                if h.state == STATE_DEAD:
                    # Idle sessions pinned to a dead replica re-place
                    # fresh; sessions with live streams are already in
                    # the failover path.
                    busy = {sid for sid, hh
                            in list(self._routes.values())
                            if hh is h}
                    self.affinity.drop_replica(h.replica_id, keep=busy)
        self.affinity.prune()
        self._update_gauges()

    def _update_gauges(self) -> None:
        self._m_available.set(
            sum(1 for h in self.replicas if h.available()))

    # ---------------- routing ----------------

    def _handle(self, replica_id: str) -> ReplicaHandle:
        for h in self.replicas:
            if h.replica_id == replica_id:
                return h
        raise KeyError(f"unknown replica {replica_id!r}")

    def _place(self, session_id: str,
               exclude: set[str]) -> ReplicaHandle:
        handle, affine = self.policy.place(session_id, self.replicas,
                                           exclude)
        if handle is None:
            self._m_sheds.inc()
            raise AdmissionRejected(
                "no healthy replica available"
                + (" (fleet is draining)" if self._draining else ""),
                retry_after=max(1.0, self.probe_interval_s or 1.0),
                reason="no_replica")
        self._m_placements.inc()
        if affine:
            self._m_affinity_hits.inc()
        return handle

    async def generate(self, request_id: str, session_id: str,
                       messages: list[dict], params: GenerationParams,
                       ) -> AsyncGenerator[dict, None]:
        if self._draining:
            self._m_sheds.inc()
            raise AdmissionRejected(
                "fleet is draining: finishing in-flight requests, not "
                "accepting new ones", retry_after=5.0, reason="draining")
        excluded: set[str] = set()
        delivered = 0          # chars already yielded to the caller
        attempt = 0
        resumed_total = 0
        pending_resume = False
        try:
            while True:
                # A cancel can land while no replica owns the stream —
                # between attempts, or while the generator is suspended
                # yielding the resumed frame. Check at every point we
                # regain control with no replica-side stream to carry
                # the cancel for us.
                if request_id in self._cancelled:
                    yield {"type": "cancelled",
                           "finish_reason": "cancelled", "stats": {}}
                    return
                handle = self._place(session_id, excluded)
                if pending_resume:
                    pending_resume = False
                    resumed_total += 1
                    self._m_resumes.inc()
                    yield {"type": "resumed",
                           "replica": handle.replica_id,
                           "attempt": attempt}
                    if request_id in self._cancelled:
                        yield {"type": "cancelled",
                               "finish_reason": "cancelled",
                               "stats": {}}
                        return
                self._routes[request_id] = (session_id, handle)
                handle.inflight.add(request_id)
                handle.placements += 1
                failure: str | None = None
                skip = delivered
                t0 = self._clock()
                try:
                    async for ev in handle.engine.generate(
                            request_id, session_id, messages, params):
                        et = ev.get("type")
                        if et == "token":
                            text = ev.get("text", "")
                            if skip > 0:  # resume overlap trim
                                if len(text) <= skip:
                                    skip -= len(text)
                                    continue
                                text = text[skip:]
                                skip = 0
                            if not text:
                                continue
                            delivered += len(text)
                            yield {**ev, "text": text}
                        elif et in ("done", "cancelled"):
                            if resumed_total:
                                ev = {**ev,
                                      "stats": {**(ev.get("stats") or {}),
                                                "resumed": resumed_total}}
                            yield ev
                            return
                        elif et == "error":
                            # code "internal_error" is emitted ONLY by
                            # the engine's crash/shutdown abort path
                            # (_abort_all) — a replica fault even when
                            # check_connection() hasn't flipped yet
                            # (the abort events race the thread's
                            # teardown). Anything else is judged by
                            # liveness: deadline_expired / stalled /
                            # validation errors from a live replica
                            # propagate.
                            if ev.get("code") == "internal_error" \
                                    or not handle.alive():
                                failure = str(ev.get("error", ""))
                                break
                            yield ev  # genuine request error: propagate
                            return
                        else:
                            yield ev  # tool_call etc.: pass through
                except asyncio.CancelledError:
                    handle.engine.cancel(request_id)
                    raise
                except AdmissionRejected:
                    # This replica's queue shed us. A fresh request can
                    # try a less-loaded replica; a resumed stream (or a
                    # fully-excluded fleet) must surface the shed with
                    # its retry_after.
                    excluded.add(handle.replica_id)
                    if delivered == 0 and len(excluded) < len(
                            self.replicas):
                        continue
                    raise
                except LLMServiceError as e:
                    if e.category in _FAULT_CATEGORIES \
                            or not handle.alive():
                        failure = str(e)
                    else:
                        raise
                except Exception as e:
                    if not handle.alive():
                        failure = str(e)
                    else:
                        raise
                finally:
                    handle.inflight.discard(request_id)
                if failure is None:
                    # Stream ended with no terminal event (a replica
                    # torn down mid-yield can do this): same treatment
                    # as an explicit failure.
                    failure = "stream ended without a terminal event"
                # ---------- failover ----------
                died = handle.note_stream_failure()
                self._m_failovers.inc()
                self._tracer.event(request_id, "failover")
                self._events.emit(
                    "router_failover", severity="critical",
                    replica=handle.replica_id, request=request_id,
                    session=session_id, mid_stream=delivered > 0,
                    attempt=attempt, error=failure[:200])
                if died:
                    busy = {sid for sid, hh
                            in list(self._routes.values())
                            if hh is handle}
                    self.affinity.drop_replica(handle.replica_id,
                                               keep=busy)
                self._update_gauges()
                log.warning(
                    f"[{request_id}] replica {handle.replica_id} failed "
                    f"{'mid-stream' if delivered else 'pre-token'} "
                    f"(attempt {attempt}): {failure}")
                if request_id in self._cancelled:
                    yield {"type": "cancelled",
                           "finish_reason": "cancelled", "stats": {}}
                    return
                excluded.add(handle.replica_id)
                attempt += 1
                if attempt > self.failover_retries:
                    yield {"type": "error",
                           "error": f"replica {handle.replica_id} "
                           f"failed and failover retries exhausted: "
                           f"{failure}",
                           "code": "replica_failed"}
                    return
                if delivered > 0:
                    if params.structured is not None:
                        # Constrained stream (docs/STRUCTURED.md): a
                        # resume re-generates the document on the
                        # survivor, and splicing a NEW document onto
                        # already-delivered text would hand the client
                        # invalid output — the one thing structured
                        # mode promises never happens. Fail the stream
                        # instead; pre-first-token failover above
                        # still re-routes silently.
                        yield {"type": "error",
                               "error": f"replica {handle.replica_id} "
                               "died mid-stream of a structured "
                               "generation (resume would break the "
                               f"validity contract): {failure}",
                               "code": "replica_failed"}
                        return
                    if not self.resume_enabled:
                        yield {"type": "error",
                               "error": f"replica {handle.replica_id} "
                               f"died mid-stream (resume disabled): "
                               f"{failure}",
                               "code": "replica_failed"}
                        return
                    # Affinity moves with the resume: the survivor
                    # re-prefills the transcript and becomes the
                    # session's home.
                    pending_resume = True
                self._tracer.add_span(request_id, "failover", t0,
                                      self._clock(),
                                      replica=handle.replica_id,
                                      mid_stream=delivered > 0)
        finally:
            self._routes.pop(request_id, None)
            self._cancelled.discard(request_id)

    # ---------------- EngineBase surface ----------------

    def cancel(self, request_id: str) -> bool:
        # Mark first: a cancel landing between failover attempts (no
        # replica owns the stream at that instant) must still terminate
        # the retry loop.
        self._cancelled.add(request_id)
        route = self._routes.get(request_id)
        if route is not None:
            try:
                return bool(route[1].engine.cancel(request_id))
            except Exception:
                return False
        return False

    def release_session(self, session_id: str) -> None:
        self.affinity.drop(session_id)
        # Fan out: a failed-over session may have parked KV on more
        # than one replica (release is idempotent everywhere).
        for h in self.replicas:
            try:
                h.engine.release_session(session_id)
            except Exception:
                pass

    def check_connection(self) -> bool:
        return self._started and any(h.available() and h.alive()
                                     for h in self.replicas)

    def get_model_info(self) -> dict:
        info: dict[str, Any] = {}
        for h in self.replicas:
            try:
                info = dict(h.engine.get_model_info())
                break
            except Exception:
                continue
        info["fleet_size"] = len(self.replicas)
        info["router"] = True
        return info

    def get_stats(self) -> dict:
        per_replica = {}
        waiting = running = 0
        for h in self.replicas:
            stats = self._safe(h, "get_stats", {}) or {}
            per_replica[h.replica_id] = {
                "state": h.state, "draining": h.draining,
                "inflight": len(h.inflight),
                "waiting": stats.get("waiting", 0),
            }
            waiting += int(stats.get("waiting", 0) or 0)
            running += int(stats.get("running", 0) or 0)
        return {
            "router": {
                "replicas": len(self.replicas),
                "available": sum(1 for h in self.replicas
                                 if h.available()),
                "dead": sum(1 for h in self.replicas
                            if h.state == STATE_DEAD),
                "affinity_sessions": len(self.affinity),
                "placements": self._m_placements.value,
                "affinity_hits": self._m_affinity_hits.value,
                "failovers": self._m_failovers.value,
                "resumes": self._m_resumes.value,
                "sheds": self._m_sheds.value,
                "draining": self._draining,
            },
            "per_replica": per_replica,
            "waiting": waiting,
            "running": running,
        }

    def fleet_stats(self) -> dict:
        """The /fleet endpoint's body: registry view with live scores."""
        replicas = []
        for h in self.replicas:
            d = h.to_dict()
            score = h.load_score()
            d["load_score"] = (None if score == float("inf")
                               else round(score, 3))
            replicas.append(d)
        return {
            "replicas": replicas,
            "affinity_sessions": len(self.affinity),
            "draining": self._draining,
            "counters": {
                "placements": self._m_placements.value,
                "affinity_hits": self._m_affinity_hits.value,
                "failovers": self._m_failovers.value,
                "resumes": self._m_resumes.value,
                "sheds": self._m_sheds.value,
            },
        }

    @staticmethod
    def _safe(h: ReplicaHandle, method: str, default):
        try:
            return getattr(h.engine, method)()
        except Exception:
            return default


def build_fleet(cfg) -> FleetRouter:
    """Construct the configured fleet: ``FLEET_REPLICAS`` in-process
    engine replicas (each its own engine instance — CPU fleets for
    test/bench, or dp-style multi-engine on real hardware) plus one
    remote replica per ``ROUTER_BACKENDS`` URL (other FastTalk servers,
    reached through the existing remote.py client protocol)."""
    from fasttalk_tpu.engine.factory import build_engine

    handles: list[ReplicaHandle] = []
    for i in range(cfg.fleet_replicas):
        handles.append(ReplicaHandle(
            f"inproc-{i}", build_engine(cfg),
            dead_probes=cfg.router_dead_probes))
    for i, url in enumerate(u.strip() for u in
                            cfg.router_backends.split(",") if u.strip()):
        handles.append(RemoteReplicaHandle(
            f"remote-{i}", url, cfg.model_name,
            dead_probes=cfg.router_dead_probes,
            timeout_s=cfg.vllm_timeout,
            max_inflight=cfg.remote_max_inflight,
            admission_timeout_s=cfg.sched_default_deadline_s,
            connect_retries=cfg.remote_connect_retries))
    return FleetRouter(
        handles,
        probe_interval_s=cfg.router_probe_interval_s,
        affinity_ttl_s=cfg.router_affinity_ttl_s,
        failover_retries=cfg.router_failover_retries,
        resume=cfg.router_resume)
