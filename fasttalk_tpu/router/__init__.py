from fasttalk_tpu.router.disagg import (DisaggController, parse_roles,
                                        tier_stats)
from fasttalk_tpu.router.elastic import ElasticScaler
from fasttalk_tpu.router.migrate import (deserialize_parked,
                                         serialize_parked, transfer)
from fasttalk_tpu.router.policy import AffinityMap, PlacementPolicy
from fasttalk_tpu.router.replica import (RemoteReplicaHandle,
                                         ReplicaHandle)
from fasttalk_tpu.router.router import FleetRouter, build_fleet

__all__ = [
    "AffinityMap", "PlacementPolicy", "ReplicaHandle",
    "RemoteReplicaHandle", "FleetRouter", "build_fleet",
    "ElasticScaler", "serialize_parked", "deserialize_parked",
    "transfer", "DisaggController", "parse_roles", "tier_stats",
]
