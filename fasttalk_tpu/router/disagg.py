"""Disaggregated prefill/decode serving: replica roles over the KV
migration wire (docs/ROUTER.md "Disaggregated prefill/decode").

Long prefills and decode streams interfere when they share a replica:
one 32k-token prefill chunk sits in front of every co-resident decode
step, and the decode streams' inter-token latency pays for it.
DistServe (OSDI'24) and Splitwise (ISCA'24) remove the interference by
splitting the two phases onto separate pools; the fleet fabric already
has the hard part — a session's KV moves between replica pools over
``/kv/parked`` with three-way migrate/re-prefill/restore pricing
(router/migrate.py, kvcache/policy.py) — so the split here is thin:

- Each replica carries a **role** — ``prefill`` | ``decode`` |
  ``mixed`` (``FLEET_ROLES`` / ``ROUTER_BACKEND_ROLES``; empty =
  all-mixed, byte-identical to the pre-disagg fleet). A prefill-role
  replica runs long-context chunked prefill with a deep queue and
  ZERO decode slots (the engine rejects anything but ``prefill_only``
  requests); decode/mixed replicas serve streams.
- The router routes a new stream whose estimated prompt length clears
  ``DISAGG_PREFILL_MIN_TOKENS`` through the **handoff**: a
  ``prefill_only`` sub-request runs on the prefill tier, parks the
  finished KV, and the parked entry migrates to a decode replica where
  the stream admits via the restore path. Short prompts place
  decode-local; radix ``prefix_key`` affinity still applies within the
  decode tier.
- The handoff is **priced** by the same EMAs as every other migration:
  expected transfer bytes (a learned bytes-per-token EMA times the
  prompt estimate) against re-prefilling on the decode tier. When the
  transfer costs more than the interference it saves (tiny prompts,
  cold or wedged channel), the stream falls back to mixed placement —
  the subsystem degrades to today's behaviour, never adds a cliff.

This module holds the role vocabulary, the per-tier aggregation the
/fleet endpoint and the elastic scaler read, and the pricing
controller; the orchestration (one client-invisible stream across the
prefill→handoff→decode lifecycle) lives in ``FleetRouter``.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable

__all__ = ["ROLES", "ROLE_PREFILL", "ROLE_DECODE", "ROLE_MIXED",
           "DECODE_ROLES", "parse_roles", "role_of", "tier_stats",
           "DisaggController"]

ROLE_PREFILL = "prefill"
ROLE_DECODE = "decode"
ROLE_MIXED = "mixed"
ROLES = (ROLE_PREFILL, ROLE_DECODE, ROLE_MIXED)
# Roles that may serve a decode stream (normal placement tier).
DECODE_ROLES = (ROLE_DECODE, ROLE_MIXED)

# Cold-start wire footprint of one prefilled token's KV rows. Real
# values depend on geometry/quantization and are learned from the
# first completed handoff; the cold default is deliberately small so
# the first long prefill takes the handoff path (handing off is also
# what produces the first measurement — the same cold-start philosophy
# as the RestorePolicy bandwidth EMAs).
_DEFAULT_BYTES_PER_TOKEN = 4096.0


def parse_roles(spec: str, count: int, what: str = "fleet") -> list[str]:
    """``"prefill,decode,decode"`` → validated role list of exactly
    ``count`` entries (empty spec = all-mixed). Raises ValueError with
    a named reason — Config.validate and build_fleet share this so a
    bad spec is one error message, not two behaviours."""
    if not spec.strip():
        return [ROLE_MIXED] * count
    roles = [r.strip().lower() for r in spec.split(",")]
    bad = [r for r in roles if r not in ROLES]
    if bad:
        raise ValueError(f"invalid replica role(s) {bad!r} for {what} "
                         f"(each must be one of {'|'.join(ROLES)})")
    if len(roles) != count:
        raise ValueError(f"{what} role list has {len(roles)} entries "
                         f"but {count} replica(s) are configured — one "
                         "role per replica, in order")
    return roles


def role_of(handle) -> str:
    """A replica handle's role; handles built before roles existed
    (tests constructing ReplicaHandle directly) default to mixed."""
    return getattr(handle, "role", ROLE_MIXED)


def tier_stats(replicas: Iterable[Any]) -> dict[str, dict[str, Any]]:
    """Per-role aggregates from the replicas' latest probe signals —
    the view ``GET /fleet`` surfaces and the elastic scaler's per-tier
    signals read: prefill scales on aggregate queue depth, decode on
    slot occupancy. Only roles present in the fleet appear."""
    tiers: dict[str, dict[str, Any]] = {}
    for h in replicas:
        t = tiers.setdefault(role_of(h), {
            "replicas": 0, "available": 0, "waiting": 0,
            "running": 0, "slots_total": 0, "inflight": 0})
        p = h.last_probe
        t["replicas"] += 1
        t["available"] += 1 if h.available() else 0
        t["waiting"] += int(p.get("waiting", 0) or 0)
        t["running"] += int(p.get("running", 0) or 0)
        t["slots_total"] += int(p.get("slots_total") or 0)
        t["inflight"] += len(h.inflight)
    for t in tiers.values():
        t["occupancy"] = (round(t["running"] / t["slots_total"], 3)
                          if t["slots_total"] else None)
    return tiers


class DisaggController:
    """The handoff decision + its learned wire-cost model.

    Owns no replicas and no orchestration — just the two questions the
    router asks per new stream: *is this prompt long enough for the
    prefill tier* (``DISAGG_PREFILL_MIN_TOKENS``) and *does the priced
    transfer beat re-prefilling on the decode tier* (the shared
    RestorePolicy EMAs, with expected bytes = prompt estimate times a
    bytes-per-token EMA learned from completed handoffs)."""

    def __init__(self, kv_policy, prefill_min_tokens: int = 512):
        self.kv_policy = kv_policy
        self.prefill_min_tokens = max(1, int(prefill_min_tokens))
        self._lock = threading.Lock()
        self._bytes_per_token = 0.0  # learned from completed handoffs
        self.handoffs = 0
        self.fallbacks = 0

    def bytes_per_token(self) -> float:
        with self._lock:
            return self._bytes_per_token or _DEFAULT_BYTES_PER_TOKEN

    def wants_handoff(self, est_tokens: int) -> bool:
        """True when a prompt of ``est_tokens`` should take the
        prefill-tier handoff path: long enough to interfere with
        decode, and the priced transfer (wire + target H2D copy)
        beats recomputing it decode-local. A ``False`` here IS the
        documented fallback to mixed placement."""
        if est_tokens < self.prefill_min_tokens:
            return False
        est_bytes = int(est_tokens * self.bytes_per_token())
        return self.kv_policy.decide(est_tokens, est_bytes,
                                     local=False,
                                     migratable=True) == "migrate"

    def note_handoff(self, kept_tokens: int, nbytes: int) -> None:
        """Feed the wire-cost model from one completed handoff (the
        migrated entry's real trusted-row count and byte size)."""
        if kept_tokens <= 0 or nbytes <= 0:
            return
        bpt = nbytes / kept_tokens
        with self._lock:
            self._bytes_per_token = bpt \
                if self._bytes_per_token == 0.0 \
                else 0.8 * self._bytes_per_token + 0.2 * bpt
            self.handoffs += 1

    def note_fallback(self) -> None:
        with self._lock:
            self.fallbacks += 1

    def stats(self) -> dict[str, Any]:
        with self._lock:
            return {
                "prefill_min_tokens": self.prefill_min_tokens,
                "bytes_per_token": round(
                    self._bytes_per_token or _DEFAULT_BYTES_PER_TOKEN,
                    1),
                "handoffs": self.handoffs,
                "fallbacks": self.fallbacks,
            }
