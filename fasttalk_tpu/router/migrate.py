"""Cross-replica KV migration: move a parked session's host-KV entry
between replica pools so failover, drain and rebalancing RESTORE
instead of re-prefilling the transcript (docs/ROUTER.md).

The channel is deliberately dumb: one parked entry (block-trimmed
int8/bf16 rows + scales + token ids, exactly what ``HostKVPool``
holds) moves from the source replica's pool to the target's. In-proc
replicas hand the numpy arrays over directly; remote replicas ship the
``serialize_parked`` wire form through the serving port's
``/kv/parked/{session_id}`` endpoints. Either way the transfer is
bracketed by the ``router.migrate_send`` / ``router.migrate_recv``
failpoints and validated before insertion, so the chaos suite can
prove the two invariants the fabric promises:

- a migration that fails (or corrupts) mid-transfer leaves byte
  accounting EXACT on both pools — the source entry is untouched until
  the target confirmed the import, and the target's ``put`` is atomic;
- a hung migration never wedges the caller — the router runs the
  transfer on a disposable worker thread bounded by
  ``ROUTER_MIGRATE_TIMEOUT_S`` and falls back to re-prefill.

Wire format: a JSON header (length-prefixed) carrying the entry
metadata + dtype/shape descriptors, followed by the raw array bytes in
declaration order. No pickle anywhere — the import side rebuilds the
arrays from the descriptors and refuses anything malformed.
"""

from __future__ import annotations

import json
import struct
import time
from dataclasses import replace
from typing import Any

from fasttalk_tpu.kvcache.hostpool import (ParkedKV, entry_problem,
                                           strip_device)
from fasttalk_tpu.utils.logger import get_logger

__all__ = ["serialize_parked", "deserialize_parked", "transfer",
           "entry_problem", "strip_device"]

log = get_logger("router.migrate")

_MAGIC = b"FTKV1"
_ARRAYS = ("k", "v", "k_scale", "v_scale")


# ---------------- wire form (remote replicas) ----------------

def serialize_parked(entry: ParkedKV) -> bytes:
    """Entry → bytes: MAGIC + u32 header length + JSON header + raw
    array bytes in ``_ARRAYS`` order. dtype travels by name (numpy
    extension dtypes like bfloat16 round-trip through ml_dtypes, which
    the jax stack always has)."""
    import numpy as np

    header: dict[str, Any] = {
        "session_id": entry.session_id,
        "tokens": list(entry.tokens),
        "kept": entry.kept,
        "bucket": entry.bucket,
        "nbytes": entry.nbytes,
        "arrays": {},
    }
    blobs: list[bytes] = []
    for name in _ARRAYS:
        arr = getattr(entry, name)
        if arr is None:
            continue
        arr = np.ascontiguousarray(arr)
        header["arrays"][name] = {"dtype": arr.dtype.name,
                                  "shape": list(arr.shape)}
        blobs.append(arr.tobytes())
    hdr = json.dumps(header).encode()
    return b"".join([_MAGIC, struct.pack("<I", len(hdr)), hdr, *blobs])


def deserialize_parked(data: bytes) -> ParkedKV:
    """bytes → entry. Raises ValueError on anything malformed —
    callers treat that exactly like a corrupt transfer (refused,
    accounting untouched)."""
    import numpy as np

    if len(data) < len(_MAGIC) + 4 or not data.startswith(_MAGIC):
        raise ValueError("not a serialized parked-KV entry")
    off = len(_MAGIC)
    (hlen,) = struct.unpack_from("<I", data, off)
    off += 4
    try:
        header = json.loads(data[off:off + hlen].decode())
    except Exception as e:
        raise ValueError(f"bad migration header: {e}") from e
    off += hlen
    arrays: dict[str, Any] = {}
    for name in _ARRAYS:
        arr_descs = header.get("arrays")
        desc = arr_descs.get(name) if isinstance(arr_descs, dict) \
            else None
        if desc is None:
            arrays[name] = None
            continue
        if not isinstance(desc, dict) or "dtype" not in desc \
                or "shape" not in desc:
            raise ValueError(f"malformed descriptor for array {name}")
        try:
            dtype = np.dtype(desc["dtype"])
        except TypeError:
            # bfloat16 and friends live in ml_dtypes, not core numpy.
            # Anything neither library knows is a malformed header and
            # must keep the ValueError contract (clean 400 refusal),
            # not leak an AttributeError into the handler.
            import ml_dtypes

            try:
                dtype = np.dtype(getattr(ml_dtypes, desc["dtype"]))
            except (AttributeError, TypeError) as e:
                raise ValueError(
                    f"unknown dtype {desc['dtype']!r} in migration "
                    "header") from e
        shape = tuple(int(s) for s in desc["shape"])
        n = int(np.prod(shape)) * dtype.itemsize
        if off + n > len(data):
            raise ValueError(f"truncated array {name}")
        arrays[name] = np.frombuffer(
            data[off:off + n], dtype=dtype).reshape(shape).copy()
        off += n
    try:
        entry = ParkedKV(
            session_id=str(header["session_id"]),
            tokens=[int(t) for t in header["tokens"]],
            kept=int(header["kept"]), bucket=int(header["bucket"]),
            k=arrays["k"], v=arrays["v"], k_scale=arrays["k_scale"],
            v_scale=arrays["v_scale"], nbytes=int(header["nbytes"]))
    except (KeyError, TypeError) as e:
        raise ValueError(f"malformed migration header: {e}") from e
    problem = entry_problem(entry)
    if problem is not None:
        raise ValueError(f"invalid migrated entry: {problem}")
    return entry


# ---------------- the transfer itself ----------------

def transfer(src, dst, session_id: str, traceparent: str | None = None,
             tracer=None,
             request_id: str = "") -> tuple[bool, int, str, int]:
    """Move one parked session's entry ``src`` → ``dst`` (replica
    handles). Returns ``(ok, nbytes, reason, kept)`` — ``kept`` is the
    moved entry's trusted-token count (0 on failure), the identity the
    router's abandoned-worker undo checks before dropping anything. On
    any failure the source entry is left in place (the caller decides
    whether drain semantics then release it) and the target pool is
    untouched.

    Runs on the router's disposable migrate worker thread — both the
    export (remote: an HTTP GET) and the import (remote: an HTTP POST)
    may block; the router bounds the whole call with its timeout.
    ``traceparent`` rides the /kv/parked wire on remote hops
    (docs/OBSERVABILITY.md "Fleet tracing"); when ``tracer`` and
    ``request_id`` are given, the two legs are recorded as
    ``migrate_send``/``migrate_recv`` spans on the request's trace —
    the caller's thread-unsafe ContextVar does not cross into this
    worker thread, so the span plumbing is explicit."""
    from fasttalk_tpu.resilience import failpoints as _fp

    def span(name: str, t0: float, **attrs) -> None:
        if tracer is not None and request_id and tracer.enabled:
            tracer.add_span(request_id, name, t0, time.monotonic(),
                            session_id=session_id, **attrs)

    t_send = time.monotonic()
    try:
        if _fp.enabled:
            # Chaos seam, source side: a dead/partitioned source looks
            # like an export failure — the fabric must fall back to
            # re-prefill with both pools' accounting intact.
            _fp.fire("router.migrate_send", session_id=session_id,
                     replica=src.replica_id)
        entry = src.export_parked(session_id, traceparent=traceparent)
    except Exception as e:
        span("migrate_send", t_send, replica=src.replica_id, ok=False)
        return False, 0, f"export failed: {e}", 0
    if entry is None:
        span("migrate_send", t_send, replica=src.replica_id, ok=False)
        return False, 0, "no parked entry", 0
    span("migrate_send", t_send, replica=src.replica_id, ok=True,
         nbytes=entry.nbytes)
    t_recv = time.monotonic()
    try:
        if _fp.enabled:
            corrupt = _fp.fire("router.migrate_recv",
                               session_id=session_id,
                               replica=dst.replica_id)
            if corrupt == "corrupt":
                # In-proc corruption: clip the token list so the
                # import validation refuses the entry (the wire form
                # corrupts the same way — a truncated body fails
                # deserialize).
                entry = replace(entry, tokens=entry.tokens[:-1])
        problem = entry_problem(entry)
        if problem is not None:
            span("migrate_recv", t_recv, replica=dst.replica_id,
                 ok=False)
            return False, 0, f"corrupt entry refused: {problem}", 0
        ok = dst.import_parked(entry, traceparent=traceparent)
    except Exception as e:
        span("migrate_recv", t_recv, replica=dst.replica_id, ok=False)
        return False, 0, f"import failed: {e}", 0
    span("migrate_recv", t_recv, replica=dst.replica_id, ok=bool(ok))
    if not ok:
        return False, 0, "target refused the entry", 0
    return True, entry.nbytes, "ok", entry.kept
