"""Placement policy: session affinity first, weighted-least-loaded else.

Affinity is the fleet-level mirror of the engine's KV residency story
(docs/KVCACHE.md): a session's resident or host-parked KV lives on ONE
replica, so routing its next turn anywhere else throws away the PR-4
restore path and re-prefills the whole history. The map is therefore
sticky for ``ttl_s`` of idleness (default matches KV_PARK_TTL_S — once
the parked entry has expired server-side there is nothing left to be
sticky to) and is dropped on release_session, replica death, and
replica drain.

New sessions place by weighted least-loaded: each candidate's
``load_score()`` (queue depth + live in-flight + overload/SLO
penalties, replica.py) is compared and the minimum wins; ties break by
rotation so equal replicas share arrivals instead of all landing on
index 0.

Shared-prefix affinity (the fleet mirror of the engine's block-
aliasing/prefix-stamp tier, docs/KVCACHE.md): sessions carrying the
same system prompt CO-LOCATE when it is nearly free — the placement
remembers which replica last served each ``prefix_key`` and prefers it
while its load score is within ``PREFIX_SLACK`` of the best candidate.
On the preferred replica the new session's prompt prefix is already
device-resident (alias stamp: zero row copies on the paged tier), so a
small amount of extra queue is cheaper than a cold prefill elsewhere;
past the slack, load wins — prefix affinity must never pile a hot
tenant onto one replica.

Thread-safety: placement runs on the asyncio loop while the probe
thread reads for pruning — one lock, a few dict ops.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Iterable

from fasttalk_tpu.router.replica import ReplicaHandle


class AffinityMap:
    """session_id → (replica_id, last_used) with TTL eviction."""

    def __init__(self, ttl_s: float = 600.0, clock=time.monotonic):
        self.ttl_s = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._map: dict[str, tuple[str, float]] = {}

    def get(self, session_id: str) -> str | None:
        now = self._clock()
        with self._lock:
            entry = self._map.get(session_id)
            if entry is None:
                return None
            replica_id, last = entry
            if now - last > self.ttl_s:
                del self._map[session_id]
                return None
            return replica_id

    def set(self, session_id: str, replica_id: str) -> None:
        with self._lock:
            self._map[session_id] = (replica_id, self._clock())

    def touch(self, session_id: str) -> None:
        with self._lock:
            entry = self._map.get(session_id)
            if entry is not None:
                self._map[session_id] = (entry[0], self._clock())

    def drop(self, session_id: str) -> None:
        with self._lock:
            self._map.pop(session_id, None)

    def drop_replica(self, replica_id: str,
                     keep: Iterable[str] = ()) -> list[str]:
        """Forget every session pinned to ``replica_id`` except those in
        ``keep`` (sessions with a stream still finishing there during a
        drain). Returns the dropped session ids."""
        keep = set(keep)
        with self._lock:
            dropped = [sid for sid, (rid, _) in self._map.items()
                       if rid == replica_id and sid not in keep]
            for sid in dropped:
                del self._map[sid]
            return dropped

    def prune(self) -> int:
        """TTL sweep (probe-thread housekeeping). Returns #evicted."""
        now = self._clock()
        with self._lock:
            stale = [sid for sid, (_, last) in self._map.items()
                     if now - last > self.ttl_s]
            for sid in stale:
                del self._map[sid]
            return len(stale)

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def snapshot(self) -> dict[str, str]:
        with self._lock:
            return {sid: rid for sid, (rid, _) in self._map.items()}


class PlacementPolicy:
    """Affinity-then-prefix-then-least-loaded placement."""

    # How much extra load score a prefix co-location may cost: one
    # queued request's worth. Past this, spreading wins — a hot tenant
    # must not pile onto one replica just to share a system prompt.
    PREFIX_SLACK = 1.0
    # prefix_key → replica_id memory is a bounded LRU: tenant count is
    # unbounded, the placement hint is best-effort.
    PREFIX_CAP = 512

    def __init__(self, affinity: AffinityMap,
                 prefix_affinity: bool = True,
                 on_prefix_hit=None):
        self.affinity = affinity
        self.prefix_affinity = prefix_affinity
        self._on_prefix_hit = on_prefix_hit
        self._prefix: "OrderedDict[str, str]" = OrderedDict()
        self._rr = 0  # tie-break rotation counter
        self._lock = threading.Lock()

    def drop_replica(self, replica_id: str) -> None:
        """Forget prefix hints pointing at a dead/drained/removed
        replica (the affinity map's drop_replica is separate)."""
        with self._lock:
            for key in [k for k, rid in self._prefix.items()
                        if rid == replica_id]:
                del self._prefix[key]

    @staticmethod
    def pick_tier(replicas: list[ReplicaHandle],
                  roles: tuple[str, ...],
                  exclude: frozenset[str] | set[str] = frozenset(),
                  ) -> ReplicaHandle | None:
        """Least-loaded available replica within a role tier, with NO
        affinity or prefix side effects — the prefill side of a
        disagg handoff (router/disagg.py) is transient by design: the
        session must end up pinned to its DECODE replica, where the
        migrated KV lives, never to the prefill replica that computed
        it."""
        candidates = [h for h in replicas
                      if h.available() and h.replica_id not in exclude
                      and getattr(h, "role", "mixed") in roles]
        if not candidates:
            return None
        return min(candidates, key=lambda h: h.load_score())

    def place(self, session_id: str, replicas: list[ReplicaHandle],
              exclude: frozenset[str] | set[str] = frozenset(),
              prefix_key: str | None = None,
              roles: tuple[str, ...] | None = None,
              ) -> tuple[ReplicaHandle | None, bool]:
        """Pick a replica for one request. Returns (handle, affine) —
        ``affine`` True when the session's pinned replica served (KV
        reuse preserved); None when no replica is placeable.
        ``prefix_key`` identifies the request's shared prefix (system
        prompt hash) for co-location. ``roles`` restricts candidates
        to replicas of those roles (disaggregated serving,
        router/disagg.py: decode streams place on the decode/mixed
        tier — a pin pointing at a prefill-role replica is ignored,
        never followed); None = role-blind (today's behaviour)."""
        def _role_ok(h: ReplicaHandle) -> bool:
            return roles is None or getattr(h, "role", "mixed") in roles

        by_id = {h.replica_id: h for h in replicas}
        pinned = self.affinity.get(session_id)
        if pinned is not None and pinned not in exclude:
            h = by_id.get(pinned)
            if h is not None and h.available() and _role_ok(h):
                self.affinity.touch(session_id)
                return h, True
        candidates = [h for h in replicas
                      if h.available() and h.replica_id not in exclude
                      and _role_ok(h)]
        if not candidates:
            return None, False
        scored = [(h.load_score(), h) for h in candidates]
        best = min(s for s, _ in scored)
        chosen: ReplicaHandle | None = None
        if self.prefix_affinity and prefix_key is not None:
            with self._lock:
                hinted = self._prefix.get(prefix_key)
            if hinted is not None:
                for s, h in scored:
                    if h.replica_id == hinted \
                            and s <= best + self.PREFIX_SLACK:
                        chosen = h
                        if self._on_prefix_hit is not None:
                            self._on_prefix_hit()
                        break
        if chosen is None:
            tied = [h for s, h in scored if s == best]
            with self._lock:
                chosen = tied[self._rr % len(tied)]
                self._rr += 1
        if self.prefix_affinity and prefix_key is not None:
            with self._lock:
                self._prefix[prefix_key] = chosen.replica_id
                self._prefix.move_to_end(prefix_key)
                while len(self._prefix) > self.PREFIX_CAP:
                    self._prefix.popitem(last=False)
        self.affinity.set(session_id, chosen.replica_id)
        return chosen, False
