"""Placement policy: session affinity first, weighted-least-loaded else.

Affinity is the fleet-level mirror of the engine's KV residency story
(docs/KVCACHE.md): a session's resident or host-parked KV lives on ONE
replica, so routing its next turn anywhere else throws away the PR-4
restore path and re-prefills the whole history. The map is therefore
sticky for ``ttl_s`` of idleness (default matches KV_PARK_TTL_S — once
the parked entry has expired server-side there is nothing left to be
sticky to) and is dropped on release_session, replica death, and
replica drain.

New sessions place by weighted least-loaded: each candidate's
``load_score()`` (queue depth + live in-flight + overload/SLO
penalties, replica.py) is compared and the minimum wins; ties break by
rotation so equal replicas share arrivals instead of all landing on
index 0.

Thread-safety: placement runs on the asyncio loop while the probe
thread reads for pruning — one lock, a few dict ops.
"""

from __future__ import annotations

import threading
import time
from typing import Iterable

from fasttalk_tpu.router.replica import ReplicaHandle


class AffinityMap:
    """session_id → (replica_id, last_used) with TTL eviction."""

    def __init__(self, ttl_s: float = 600.0, clock=time.monotonic):
        self.ttl_s = ttl_s
        self._clock = clock
        self._lock = threading.Lock()
        self._map: dict[str, tuple[str, float]] = {}

    def get(self, session_id: str) -> str | None:
        now = self._clock()
        with self._lock:
            entry = self._map.get(session_id)
            if entry is None:
                return None
            replica_id, last = entry
            if now - last > self.ttl_s:
                del self._map[session_id]
                return None
            return replica_id

    def set(self, session_id: str, replica_id: str) -> None:
        with self._lock:
            self._map[session_id] = (replica_id, self._clock())

    def touch(self, session_id: str) -> None:
        with self._lock:
            entry = self._map.get(session_id)
            if entry is not None:
                self._map[session_id] = (entry[0], self._clock())

    def drop(self, session_id: str) -> None:
        with self._lock:
            self._map.pop(session_id, None)

    def drop_replica(self, replica_id: str,
                     keep: Iterable[str] = ()) -> list[str]:
        """Forget every session pinned to ``replica_id`` except those in
        ``keep`` (sessions with a stream still finishing there during a
        drain). Returns the dropped session ids."""
        keep = set(keep)
        with self._lock:
            dropped = [sid for sid, (rid, _) in self._map.items()
                       if rid == replica_id and sid not in keep]
            for sid in dropped:
                del self._map[sid]
            return dropped

    def prune(self) -> int:
        """TTL sweep (probe-thread housekeeping). Returns #evicted."""
        now = self._clock()
        with self._lock:
            stale = [sid for sid, (_, last) in self._map.items()
                     if now - last > self.ttl_s]
            for sid in stale:
                del self._map[sid]
            return len(stale)

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def snapshot(self) -> dict[str, str]:
        with self._lock:
            return {sid: rid for sid, (rid, _) in self._map.items()}


class PlacementPolicy:
    """Affinity-then-least-loaded placement over a replica list."""

    def __init__(self, affinity: AffinityMap):
        self.affinity = affinity
        self._rr = 0  # tie-break rotation counter
        self._lock = threading.Lock()

    def place(self, session_id: str, replicas: list[ReplicaHandle],
              exclude: frozenset[str] | set[str] = frozenset(),
              ) -> tuple[ReplicaHandle | None, bool]:
        """Pick a replica for one request. Returns (handle, affine) —
        ``affine`` True when the session's pinned replica served (KV
        reuse preserved); None when no replica is placeable."""
        by_id = {h.replica_id: h for h in replicas}
        pinned = self.affinity.get(session_id)
        if pinned is not None and pinned not in exclude:
            h = by_id.get(pinned)
            if h is not None and h.available():
                self.affinity.touch(session_id)
                return h, True
        candidates = [h for h in replicas
                      if h.available() and h.replica_id not in exclude]
        if not candidates:
            return None, False
        scored = [(h.load_score(), h) for h in candidates]
        best = min(s for s, _ in scored)
        tied = [h for s, h in scored if s == best]
        with self._lock:
            h = tied[self._rr % len(tied)]
            self._rr += 1
        self.affinity.set(session_id, h.replica_id)
        return h, False
