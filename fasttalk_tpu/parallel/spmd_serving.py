"""Multi-host SPMD serving: leader/follower device-call replication.

Multi-controller JAX requires every process of a cluster to execute the
same sequence of jitted computations (collectives rendezvous across
hosts). A serving engine is the opposite of lockstep: its dispatch
decisions depend on request arrival timing, fetch completion, queue
depth. The resolution here is that followers do not DECIDE anything —
the leader's engine thread publishes a compact descriptor of every
device call it makes (which compiled program + the host-side arguments;
device-side state is chained locally on every host by construction),
and followers replay exactly that sequence against their own shards.
Sampled tokens leave the engine's mesh programs fully replicated, so
the leader serves every client from its local shard while followers
contribute their slice of the model compute over DCN/ICI.

This is the multi-host scale-out story the reference delegated wholesale
to vLLM's --tensor-parallel-size flag (reference
docker-compose.vllm.yml:42): here the gateway and the multi-host engine
are one process tree, and tests/test_spmd_serving.py proves the FULL
serving loop — admission, batched prefill, continuous-batching decode,
EOS retirement — across two real OS processes with stream parity
against a single-process run.

Cluster liveness (VERDICT item 7, docs/RESILIENCE.md):
- The leader's broadcaster sends a small ``hb`` frame every
  ``SPMD_HB_INTERVAL_S`` (default 2 s) even when no device calls are
  being published, so a dead follower socket is discovered by a failed
  send within a couple of intervals instead of "whenever the next
  collective times out".
- A follower applies ``SPMD_HB_TIMEOUT_S`` (default 8 s) as a recv
  deadline: a leader that stops publishing (crashed, hung, partitioned)
  surfaces as a ConnectionError within the deadline, not a forever-
  blocked recv.
- A dead follower is **fatal for the cluster**: its shards stop
  advancing, so per-host state can no longer stay identical. The
  broadcaster sends an ``abort`` frame to the survivors, marks itself
  dead, and every later publish raises — the engine thread crashes
  through its ordinary terminal-event path and the launcher shuts the
  gateway down for a cluster restart (the previous behaviour silently
  dropped the follower and served a corrupted cluster until a
  collective eventually hung).

Scope and limits (stated, not hidden):
- The wire format is pickle over a loopback/trusted-network TCP socket
  (cluster-internal, like the reference's NCCL/MPI planes); do not
  expose it publicly.
- Supervised in-place engine restart is leader-local state surgery and
  is not replicated; multi-host recovery is a cluster restart, like
  the reference's container restart policy.
"""

from __future__ import annotations

import pickle
import queue
import socket
import struct
import threading
import time
from typing import Any

import numpy as np

from fasttalk_tpu.resilience import failpoints as _fp
# Env fallbacks are for standalone/test construction only — the
# launcher passes the VALIDATED Config values (spmd_hb_interval_s /
# spmd_hb_timeout_s) explicitly, which is the production path.
from fasttalk_tpu.utils.config import _env_float
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("parallel.spmd_serving")

_LEN = struct.Struct("!I")


def _send(conn: socket.socket, obj: Any) -> None:
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    conn.sendall(_LEN.pack(len(payload)) + payload)


def _recv(conn: socket.socket, deadline_s: float | None = None) -> Any:
    """Read one frame. ``deadline_s`` bounds how long we wait for the
    FIRST byte (and each subsequent chunk): with leader heartbeats on
    the wire, a silent peer past the deadline is a dead peer — surface
    a ConnectionError now instead of blocking until some collective
    times out."""
    if _fp.enabled:
        _fp.fire("spmd.recv", exc=ConnectionError)
    # Unconditional: deadline_s=None must mean a BLOCKING recv even on
    # a socket still carrying a connect-time timeout
    # (socket.create_connection(timeout=...) lingers otherwise).
    conn.settimeout(deadline_s)
    try:
        head = b""
        while len(head) < _LEN.size:
            chunk = conn.recv(_LEN.size - len(head))
            if not chunk:
                raise ConnectionError("spmd_serving: peer closed")
            head += chunk
        (n,) = _LEN.unpack(head)
        buf = bytearray()
        while len(buf) < n:
            chunk = conn.recv(min(1 << 20, n - len(buf)))
            if not chunk:
                raise ConnectionError(
                    "spmd_serving: peer closed mid-frame")
            buf += chunk
        return pickle.loads(bytes(buf))
    except (socket.timeout, TimeoutError) as e:
        # deadline_s can only be None here via an exotic caller-set
        # socket timeout; format defensively so the diagnostic is
        # never masked by a TypeError in its own handler.
        within = f"{deadline_s:.1f}s" if deadline_s else "the deadline"
        raise ConnectionError(
            f"spmd_serving: no frame from peer within {within} "
            "(heartbeat deadline) — peer presumed dead") from e


class CallBroadcaster:
    """Leader side: accepts follower connections, then fans every
    engine device-call descriptor out to all of them.

    Attached to the engine as ``engine.call_sink``; the engine thread
    only ENQUEUES — a dedicated sender thread serializes and writes,
    so a stalled follower's TCP window never back-pressures the
    dispatch path, and frame order (including abort-before-dispatch)
    is preserved by the single queue. A heartbeat thread keeps frames
    on the wire while the engine is idle, so follower death is
    detected by a failed send within ~2 heartbeat intervals. A
    follower whose socket errors is **fatal for the cluster**
    (module-scope liveness note): the survivors get an abort frame,
    ``dead_reason`` is set, and every later publish raises.
    ``close()`` may be called from any thread; it flushes the queue,
    sends the stop frame, and joins the sender."""

    def __init__(self, host: str, port: int, n_followers: int,
                 accept_timeout_s: float = 300.0,
                 hb_interval_s: float | None = None):
        self.hb_interval_s = (hb_interval_s if hb_interval_s is not None
                              else _env_float("SPMD_HB_INTERVAL_S", 2.0))
        self.dead_reason: str | None = None
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(accept_timeout_s)
        self._closed = False
        self._conns: list[socket.socket] = []
        log.info(f"spmd leader waiting for {n_followers} follower(s) "
                 f"on {host}:{port}")
        for i in range(n_followers):
            try:
                conn, addr = self._srv.accept()
            except TimeoutError:
                self._srv.close()
                raise TimeoutError(
                    f"spmd_serving: follower {i + 1}/{n_followers} did "
                    f"not connect within {accept_timeout_s:.0f}s — is "
                    "the follower process up and pointed at "
                    f"{host}:{port}?") from None
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # Send timeout: a HUNG follower (SIGSTOP, wedged device
            # call) fills its TCP window without ever closing the
            # socket — sendall would block _pump forever and the
            # liveness hole would be back for the hang class. A
            # stalled send past this bound raises socket.timeout
            # (an OSError), which _pump turns into _fatal.
            conn.settimeout(max(30.0, 5.0 * self.hb_interval_s))
            self._conns.append(conn)
            log.info(f"spmd follower connected from {addr}")
        self._q: queue.Queue = queue.Queue()
        # First frame on the wire: the leader's heartbeat contract.
        # The INTERVAL is a leader-side setting — followers must not
        # guess it from their own env (a leader with the beacon off
        # and a follower holding the default deadline would declare a
        # healthy idle cluster dead).
        self._q.put(("hello", {"hb_interval_s": self.hb_interval_s}))
        self._sender = threading.Thread(target=self._pump,
                                        name="spmd-sender", daemon=True)
        self._sender.start()
        self._hb = threading.Thread(target=self._heartbeat,
                                    name="spmd-hb", daemon=True)
        self._hb.start()

    @property
    def port(self) -> int:
        return self._srv.getsockname()[1]

    def _heartbeat(self) -> None:
        """Leader liveness beacon: one tiny frame per interval,
        regardless of engine activity. Followers skip it; its real job
        is keeping the TCP stream active so a dead follower trips a
        send error promptly (and giving followers a frame to apply
        their recv deadline against)."""
        if self.hb_interval_s <= 0:
            return
        while not self._closed and self.dead_reason is None:
            time.sleep(self.hb_interval_s)
            if self._closed or self.dead_reason is not None:
                return
            self._q.put(("hb", {}))

    def _pump(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            if self.dead_reason is not None:
                continue  # drain post-fatal enqueues silently
            payload = pickle.dumps(item,
                                   protocol=pickle.HIGHEST_PROTOCOL)
            frame = _LEN.pack(len(payload)) + payload
            for conn in list(self._conns):
                try:
                    if _fp.enabled:
                        _fp.fire("spmd.send", exc=OSError)
                    conn.sendall(frame)
                except OSError as e:
                    # A lost follower's shards stop advancing, so
                    # per-host device state can no longer be identical:
                    # the CLUSTER is dead, not just that socket
                    # (replaying further calls against the survivors
                    # would serve a corrupted cluster until a
                    # collective eventually hung — the exact liveness
                    # hole this closes, VERDICT item 7).
                    self._fatal(f"follower send failed: {e}")
                    break

    def _fatal(self, reason: str) -> None:
        """Mark the cluster dead: abort the surviving followers, close
        every socket, and make later publishes raise (the engine
        thread then crashes through its terminal-event path and the
        launcher shuts the gateway down for a cluster restart)."""
        self.dead_reason = reason
        log.critical(f"spmd cluster dead: {reason}; aborting followers "
                     "and refusing further publishes")
        try:
            from fasttalk_tpu.observability.events import get_events

            get_events().emit("spmd_cluster_dead", severity="critical",
                              reason=reason)
        except Exception:
            pass
        abort = pickle.dumps(("abort", {"reason": reason}),
                             protocol=pickle.HIGHEST_PROTOCOL)
        frame = _LEN.pack(len(abort)) + abort
        for conn in self._conns:
            try:
                conn.sendall(frame)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._conns.clear()

    def __call__(self, kind: str, payload: dict) -> None:
        if self._closed:
            raise RuntimeError("spmd_serving: publish after close()")
        if self.dead_reason is not None:
            raise RuntimeError(
                f"spmd_serving: cluster is dead ({self.dead_reason}); "
                "restart the cluster")
        self._q.put((kind, payload))

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._q.put(("stop", {}))
        self._q.put(None)
        self._sender.join(timeout=30)
        for conn in self._conns:
            try:
                conn.close()
            except OSError:
                pass
        self._srv.close()


def follower_loop(engine, host: str, port: int,
                  connect_timeout_s: float = 300.0,
                  hb_timeout_s: float | None = None) -> int:
    """Follower side: connect to the leader and replay its device-call
    stream against this process's engine (same construction, same
    seed, never ``start()``ed — the leader's engine thread is the only
    decision-maker in the cluster). Returns the number of calls
    replayed. Blocks until the leader sends "stop".

    ``hb_timeout_s`` (default ``SPMD_HB_TIMEOUT_S``, 8 s) is the recv
    deadline: the leader heartbeats every SPMD_HB_INTERVAL_S, so a
    silent leader past the deadline is dead — the follower raises a
    ConnectionError and exits for a cluster restart instead of
    blocking in recv until a collective times out.

    The connect retries: leader and follower build their engines
    concurrently (the builds rendezvous on collectives), and the
    leader binds its broadcast socket only after ITS build returns —
    a follower that gets there first must wait, not die."""
    if hb_timeout_s is None:
        hb_timeout_s = _env_float("SPMD_HB_TIMEOUT_S", 8.0)
    deadline = time.monotonic() + connect_timeout_s
    while True:
        try:
            conn = socket.create_connection((host, port), timeout=10)
            break
        except (ConnectionRefusedError, socket.timeout, OSError):
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"spmd_serving: leader at {host}:{port} not "
                    f"accepting within {connect_timeout_s:.0f}s")
            time.sleep(0.5)
    conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    e = engine
    last_logits = None  # register: chunked-prefill → sample_place
    n = 0
    first = True
    while True:
        # The FIRST frame gets no heartbeat deadline: the leader's
        # broadcaster (and therefore its beacon) only starts after ALL
        # followers have connected, and a sibling may lawfully take up
        # to the leader's accept timeout to arrive — only once frames
        # are flowing does silence mean death.
        kind, p = _recv(conn,
                        deadline_s=None if first
                        else (hb_timeout_s or None))
        first = False
        if kind == "hello":
            # The leader's heartbeat contract (authoritative — each
            # side's env may lawfully differ): beacon OFF means no
            # heartbeats will ever satisfy a deadline, so disable
            # ours; beacon slower than our deadline would declare a
            # healthy idle leader dead, so clamp the deadline to
            # comfortably exceed the advertised interval.
            interval = float(p.get("hb_interval_s", 0.0) or 0.0)
            if interval <= 0:
                hb_timeout_s = 0.0
            elif hb_timeout_s:
                hb_timeout_s = max(hb_timeout_s, 2.5 * interval)
            continue
        if kind == "hb":
            continue  # leader liveness beacon, not a call
        if kind == "stop":
            conn.close()
            log.info(f"spmd follower replayed {n} calls")
            return n
        if kind == "abort":
            # The leader hit a dispatch error AFTER publishing a call:
            # per-host device state can no longer be assumed identical.
            # Fail loudly; multi-host recovery is a cluster restart
            # (module scope note).
            conn.close()
            raise RuntimeError(
                f"spmd_serving: leader aborted the cluster after a "
                f"dispatch error: {p.get('reason')!r}")
        n += 1
        if kind == "decode":
            fn = e._get_decode_fn(p["kv_len"], p["steps"],
                                  p["with_history"])
            if p["with_history"]:
                (e.cache, e._history_dev, e._counts_dev, _toks,
                 e._cur_tokens, e._positions_dev, e._rng_dev) = fn(
                    e.params, e.cache, e._history_dev, e._counts_dev,
                    e._cur_tokens, e._positions_dev, e._active_dev,
                    e._temps_dev, e._topks_dev, e._topps_dev,
                    e._reps_dev, e._press_dev, e._freqs_dev, e._rng_dev)
            else:
                (e.cache, e._counts_dev, _toks, e._cur_tokens,
                 e._positions_dev, e._rng_dev) = fn(
                    e.params, e.cache, e._counts_dev, e._cur_tokens,
                    e._positions_dev, e._active_dev, e._temps_dev,
                    e._topks_dev, e._topps_dev, e._reps_dev,
                    e._press_dev, e._freqs_dev, e._rng_dev)
        elif kind == "spec":
            fn = e._get_spec_decode_fn(p["kv_len"], p["steps"])
            (e.cache, e._history_dev, e._counts_dev, _toks,
             e._cur_tokens, e._positions_dev, e._rng_dev) = fn(
                e.params, e.cache, e._history_dev, e._counts_dev,
                e._cur_tokens, e._positions_dev, e._active_dev,
                e._temps_dev, e._topks_dev, e._topps_dev, e._reps_dev,
                e._press_dev, e._freqs_dev, e._rng_dev)
        elif kind == "batched_prefill":
            fn = e._get_batched_prefill_fn(p["bucket"], p["gp"],
                                           p["ctx"])
            (e.cache, _firsts, e._cur_tokens, e._rng_dev) = fn(
                e.params, e.cache, e._arg(p["tokens"]),
                e._arg(p["rowcfg"]), e._cur_tokens, e._rng_dev)
        elif kind == "prefill":
            fn = e._get_prefill_fn(p["bucket"])
            e.cache, last_logits = fn(
                e.params, e.cache, e._arg(p["tokens"]),
                np.int32(p["start"]), np.int32(p["slot"]),
                np.int32(p["last"]))
        elif kind == "ring_prefill":
            fn = e._get_ring_prefill_fn(p["bucket"])
            e.cache, last_logits = fn(
                e.params, e.cache, e._arg(p["tokens"]),
                np.int32(p["slot"]), np.int32(p["last"]))
        elif kind == "sample_place":
            _first, e._cur_tokens, e._rng_dev = \
                e._get_sample_place_fn()(
                    last_logits, e._cur_tokens, e._rng_dev,
                    e._arg(p["cfg_row"]))
        elif kind == "prefix_copy":
            e.cache = e._get_prefix_copy_fn(p["share"])(
                e.cache, np.int32(p["src"]), np.int32(p["dst"]),
                np.int32(p.get("off", 0)))
        elif kind == "patch":
            (e._counts_dev, e._positions_dev, e._active_dev,
             e._temps_dev, e._topks_dev, e._topps_dev, e._reps_dev,
             e._press_dev, e._freqs_dev) = e._get_patch_fn()(
                e._arg(p["packed"]), e._counts_dev, e._positions_dev,
                e._active_dev, e._temps_dev, e._topks_dev,
                e._topps_dev, e._reps_dev, e._press_dev, e._freqs_dev)
        elif kind == "hist_patch":
            e._history_dev = e._get_hist_patch_fn(p["rb"])(
                e._history_dev, e._arg(p["rows"]), e._arg(p["slots"]))
        else:
            raise ValueError(f"spmd_serving: unknown call {kind!r}")
