"""Sharded training/fine-tuning step over a ("dp", "sp", "tp") mesh.

The serving framework's models are trainable with the same param pytree
and forward pass the engine serves (models/llama.py) — no separate
"training model". Parallelism is pure sharding annotation:

- params sharded per `parallel.sharding.param_pspecs` (TP);
- the token batch sharded ("dp" over batch rows, "sp" over sequence);
- optax state inherits param shardings (`optimizer.init` is
  `tree_map(zeros_like)`, which preserves placement);
- GSPMD lowers the rest to ICI collectives: all-reduce of row-parallel
  matmuls (TP), all-gather of K/V along "sp" for attention, and gradient
  all-reduce over "dp".

The explicit-schedule ring attention variant for sequences that do not
fit one chip lives in `parallel.ring_attention` and is exercised by the
long-context tests; this step uses GSPMD's all-to-all/all-gather form.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fasttalk_tpu.models.configs import ModelConfig
from fasttalk_tpu.models.llama import KVCache, forward
from fasttalk_tpu.parallel.sharding import param_pspecs, shard_params


def causal_lm_loss(params: Any, cfg: ModelConfig, tokens: jnp.ndarray,
                   loss_mask: jnp.ndarray | None = None) -> jnp.ndarray:
    """Next-token cross-entropy over ``tokens`` [B, T]. ``loss_mask``
    [B, T-1] weights target positions (1 = count)."""
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    b, t = inputs.shape
    positions = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))
    # K/V written from activations; final_norm is never quantized, so
    # its dtype is the activation dtype even when embed is a {q, s} dict.
    kv_dtype = params["final_norm"].dtype
    empty = KVCache(
        k=jnp.zeros((cfg.num_layers, b, t, cfg.num_kv_heads, cfg.head_dim),
                    kv_dtype),
        v=jnp.zeros((cfg.num_layers, b, t, cfg.num_kv_heads, cfg.head_dim),
                    kv_dtype))
    logits, _ = forward(params, cfg, inputs, positions, empty,
                        jnp.zeros((b,), jnp.int32))
    losses = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    if loss_mask is None:
        return losses.mean()
    return (losses * loss_mask).sum() / jnp.maximum(loss_mask.sum(), 1.0)


def make_train_step(cfg: ModelConfig, optimizer: optax.GradientTransformation,
                    mesh: Mesh) -> Callable:
    """Build the jitted sharded train step:
    ``(params, opt_state, tokens) -> (params, opt_state, loss)``.

    Call with params already sharded (see `init_sharded_training`); the
    donated params/opt_state keep their layouts across steps, so weights
    never leave the mesh between updates.
    """
    batch_sharding = NamedSharding(mesh, P("dp", "sp"))

    @partial(jax.jit, donate_argnums=(0, 1))
    def train_step(params, opt_state, tokens):
        tokens = jax.lax.with_sharding_constraint(tokens, batch_sharding)
        loss, grads = jax.value_and_grad(causal_lm_loss)(params, cfg, tokens)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step


def init_sharded_training(cfg: ModelConfig, params: Any, mesh: Mesh,
                          learning_rate: float = 1e-4,
                          ) -> tuple[Any, Any, optax.GradientTransformation]:
    """Shard params onto the mesh and build matching optimizer state."""
    params = shard_params(params, mesh)
    optimizer = optax.adamw(learning_rate)
    opt_state = optimizer.init(params)  # zeros_like → inherits shardings
    return params, opt_state, optimizer


def eval_step(cfg: ModelConfig, mesh: Mesh) -> Callable:
    """Jitted sharded eval loss: ``(params, tokens) -> loss``."""
    batch_sharding = NamedSharding(mesh, P("dp", "sp"))

    @jax.jit
    def step(params, tokens):
        tokens = jax.lax.with_sharding_constraint(tokens, batch_sharding)
        return causal_lm_loss(params, cfg, tokens)

    return step


__all__ = ["causal_lm_loss", "make_train_step", "init_sharded_training",
           "eval_step", "param_pspecs"]
