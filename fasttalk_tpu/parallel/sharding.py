"""Sharding rules for the Llama parameter pytree and KV cache.

Megatron-style tensor parallelism, expressed as PartitionSpecs and left
to GSPMD to lower into ICI collectives (the idiomatic TPU replacement for
the NCCL all-reduces inside the reference's vLLM container):

- wq/wk/wv and w_gate/w_up are column-parallel (output axis sharded over
  "tp") — each chip computes its own heads / FFN slice with no
  communication.
- wo and w_down are row-parallel (contraction axis sharded) — XLA emits
  one all-reduce per block to rejoin the residual stream.
- The embedding is sharded over the hidden axis, so with tied embeddings
  the output head is automatically row-parallel (partial logits +
  all-reduce); an untied lm_head is column-parallel over vocab.
- KV cache shards over KV heads on "tp" and slots on "dp"; with GQA
  (8 KV heads on every production config, models/configs.py) TP≤8
  divides evenly.

Norm scales and rope tables are tiny and stay replicated.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from fasttalk_tpu.models.llama import KVCache

# Rules keyed by parameter leaf name; specs include the leading stacked
# layer axis for everything under "layers".
_LAYER_RULES: dict[str, P] = {
    "attn_norm": P(None, None),
    "mlp_norm": P(None, None),
    "wq": P(None, None, "tp"),
    "wk": P(None, None, "tp"),
    "wv": P(None, None, "tp"),
    # Column-parallel biases shard with their matmul's output axis.
    "bq": P(None, "tp"),
    "bk": P(None, "tp"),
    "bv": P(None, "tp"),
    "wo": P(None, "tp", None),
    "w_gate": P(None, None, "tp"),
    "w_up": P(None, None, "tp"),
    "w_down": P(None, "tp", None),
}
_TOP_RULES: dict[str, P] = {
    "embed": P(None, "tp"),
    "final_norm": P(None),
    "lm_head": P(None, "tp"),
}


def _leaf_name(path) -> str:
    for entry in reversed(path):
        if hasattr(entry, "key"):
            return str(entry.key)
    return ""


def _parent_name(path) -> str:
    keys = [str(e.key) for e in path if hasattr(e, "key")]
    return keys[-2] if len(keys) >= 2 else ""


def _spec_for(name: str, ndim: int, shape=None, parent: str = "") -> P:
    """The PartitionSpec for a parameter leaf name (unknown: replicate).

    Int8-quantized leaves (ops/quant.py) appear as {"q", "s"} dicts under
    the weight's name: "q" shards exactly like the original weight; the
    per-output-channel scale "s" shards like the weight's last axis.

    Int4 leaves (fasttalk_tpu/quantization/int4.py) appear as
    {"q4", "s"}: the nibble packing pairs ADJACENT contraction rows, so
    a contiguous packed-row shard maps to a contiguous original-row
    shard and "q4" reuses the weight's own spec unchanged; the rank-3
    group scale [..., K/G, N] hits the generic scale branch below,
    which keeps base[:-1] — the group axis inherits the contraction
    axis's placement (sharded over "tp" for row-parallel wo/w_down,
    replicated for column-parallel leaves), exactly where its rows
    live. ``validate_int4_tp`` checks the divisibility those shards
    need.
    """
    if name in ("q", "qt", "q4", "s") and parent:
        base = _TOP_RULES.get(parent) or _LAYER_RULES.get(parent)
        if base is not None:
            if name == "qt":
                # Transposed untied lm_head [V, D] (ops/quant.py
                # _quantize_head_t): vocab axis stays TP-sharded,
                # now leading.
                spec = P(base[-1], *base[:-1])
            elif name in ("q", "q4"):
                spec = base
            elif parent == "embed":
                # Embedding quantizes per ROW (ops/quant.py): the scale
                # indexes the replicated vocab axis, not the TP-sharded
                # hidden axis — and at [V] f32 it is small enough to
                # replicate outright.
                spec = P(None)
            else:  # scale: leading stacked-layer axis (if any) + out axis
                spec = P(*base[:ndim - 1], base[-1])
            if len(spec) != ndim:
                raise ValueError(
                    f"spec {spec} rank mismatch for {parent}/{name} "
                    f"with shape {shape}")
            return spec
    spec = _TOP_RULES.get(name) or _LAYER_RULES.get(name)
    if spec is None:
        return P(*([None] * ndim))
    if len(spec) != ndim:
        raise ValueError(
            f"spec {spec} rank mismatch for {name} with shape {shape}")
    return spec


def param_pspecs(params: Any) -> Any:
    """PartitionSpec pytree matching ``params`` (models/llama.py
    init_params / models/loader.py structure)."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: _spec_for(_leaf_name(path), leaf.ndim, leaf.shape,
                                     parent=_parent_name(path)),
        params)


def cache_pspecs() -> KVCache:
    """Cache layout [L, slots, S, kv_heads, head_dim]: slots over "dp",
    sequence over "sp", KV heads over "tp"."""
    spec = P(None, "dp", "sp", "tp", None)
    return KVCache(k=spec, v=spec)


def shard_params(params: Any, mesh: Mesh) -> Any:
    specs = param_pspecs(params)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), params, specs)


def shard_cache(cache: KVCache, mesh: Mesh) -> KVCache:
    specs = cache_pspecs()
    return KVCache(
        k=jax.device_put(cache.k, NamedSharding(mesh, specs.k)),
        v=jax.device_put(cache.v, NamedSharding(mesh, specs.v)))


def validate_tp(tp: int, num_kv_heads: int, num_heads: int,
                hidden: int, intermediate: int,
                vocab: int | None = None) -> None:
    """Fail fast on meshes the model can't shard evenly (the reference
    left this to vLLM to discover at container boot)."""
    dims = [(num_kv_heads, "num_kv_heads"), (num_heads, "num_heads"),
            (hidden, "hidden_size"), (intermediate, "intermediate_size")]
    if vocab is not None:
        dims.append((vocab, "vocab_size"))  # lm_head is vocab-sharded
    for dim, label in dims:
        if dim % tp:
            raise ValueError(f"tp={tp} does not divide {label}={dim}")


def validate_int4_tp(tp: int, *, q_dim: int, intermediate: int,
                     group: int) -> None:
    """Divisibility the int4 leaves add on top of ``validate_tp``: the
    row-parallel weights (wo, w_down) shard their PACKED contraction
    axis and their group-scale axis over "tp", so tp must divide both
    the packed row count (dim/2 — a shard boundary must never split a
    nibble pair) and the group count (dim/group — nor split a scale
    group)."""
    for dim, label in ((q_dim, "q_dim (wo)"),
                       (intermediate, "intermediate_size (w_down)")):
        if (dim // 2) % tp:
            raise ValueError(
                f"tp={tp} does not divide the packed int4 row count "
                f"{label.split(' ')[0]}/2={dim // 2} for {label}; a shard "
                f"boundary would split a nibble pair")
        if (dim // group) % tp:
            raise ValueError(
                f"tp={tp} does not divide the int4 scale-group count "
                f"{dim}//{group}={dim // group} for {label}; a shard "
                f"boundary would split a scale group")


def validate_mesh(mesh: Mesh, *, num_kv_heads: int, num_heads: int,
                  hidden: int, intermediate: int, vocab: int,
                  num_slots: int, max_len: int) -> None:
    """Validate every mesh axis against the tensors it shards, so a bad
    TPU_TP_SIZE/TPU_DP_SIZE fails with a named message at engine build
    instead of an opaque device_put error mid-startup."""
    validate_tp(mesh.shape.get("tp", 1), num_kv_heads, num_heads, hidden,
                intermediate, vocab)
    dp = mesh.shape.get("dp", 1)
    if num_slots % dp:
        raise ValueError(
            f"dp={dp} does not divide decode_slots={num_slots}")
    sp = mesh.shape.get("sp", 1)
    if max_len % sp:
        raise ValueError(f"sp={sp} does not divide max_model_len={max_len}")


def param_put(mesh: Mesh, dtype: Any = None):
    """A ``put(host_array, path) -> jax.Array`` hook for
    ``models.loader.load_params`` that places each weight directly into
    its TP shards — each device receives only its slice, so a 70B
    checkpoint loads onto a v5e-8 without ever materialising a full
    tensor on one chip. ``dtype`` casts on placement (checkpoint tensors
    arrive host-side as float32; the engine serves bfloat16)."""
    import jax.numpy as jnp

    def put(arr, path: str) -> jax.Array:
        parts = path.split("/")
        parent = parts[-2] if len(parts) >= 2 else ""
        spec = _spec_for(parts[-1], arr.ndim, getattr(arr, "shape", None),
                         parent=parent)
        return jax.device_put(jnp.asarray(arr, dtype),
                              NamedSharding(mesh, spec))

    return put
