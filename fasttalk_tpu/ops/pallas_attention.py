"""Pallas TPU kernel for batched decode attention with per-slot lengths.

The decode step (one token per slot against the resident KV cache) is
HBM-bandwidth-bound: its cost is dominated by streaming K/V out of HBM.
The XLA path (`ops.attention.attend`) must read the whole KV-length
bucket for every slot and mask the dead tail; this kernel instead
prefetches the per-slot true lengths as scalars and prunes at the block
level — a slot at position 600 in an 8192 bucket reads 5 blocks of K/V,
not 64. Pruned grid steps remap their BlockSpec index to the slot's last
live block, so Pallas's revisiting rule elides the DMA entirely.

Two generalisations beyond the q_len=1 bf16 original (docs/ROOFLINE.md):

- **Fused int8-KV dequant** (``k_scale``/``v_scale`` operands): the
  int8 KV tier's rows stream into VMEM still quantized and dequantize
  inside the kernel after the DMA, so int8 bytes — not bf16 — are what
  cross HBM on the attention read. Scales are per-row (granule
  ``token``: G=1, or ``head``: G=num_kv_heads, ops/kv_quant.py); the
  paged variant reads them in per-block-row pool layout.
- **Multi-token q blocks** (q [B, T, Nq, D], small static T): the
  spec-decode verify block (current + draft tokens) and any short
  decode block run through the kernel, causal WITHIN the block by
  per-query horizon masking. T=1 remains the plain decode step.

Per-step layout (one grid cell = one (slot, key block); all kv heads of
the block are processed in one cell, statically unrolled — Mosaic
requires the last two dims of every block to be (multiples of 8, 128) or
equal to the array dims, which rules out blocking the kv-head axis to 1):

    q      [B, Nkv, T*G, D]  VMEM block [1, Nkv, T*G, D]  (q rows
                             t-major per kv head: row = t * G + g)
    k, v   [B, S, Nkv, D]    VMEM block [1, blk, Nkv, D]  (cache layout,
                             no transpose of the resident cache)
    scales [B, S, G]         VMEM block [1, blk, G]       (int8 tier)
    out    [B, Nkv, T*G, D]  VMEM block [1, Nkv, T*G, D]

The kv-block axis is the innermost grid dimension, so the flash-style
online-softmax state (m, l, acc) lives in VMEM scratch and carries
across blocks of the same slot; it is initialised at block 0 and
normalised into the output at the last block.

Replaces capability the reference delegated to vLLM's PagedAttention
CUDA kernels (SURVEY.md §2: in-tree native components NONE; attention
lived in the external container). Single-device only: under a TP mesh
GSPMD cannot partition a custom kernel, so the engine keeps the XLA
path when a mesh is set (the all-reduce-fused XLA attention is the
right answer there anyway).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _decode_kernel(lengths_ref, q_ref, k_ref, v_ref, *rest,
                   block_size: int, scale: float, group: int):
    """Shared online-softmax recurrence for the dense and paged kernels.

    ``rest`` is (o, m, l, acc) for the bf16 tier or
    (k_scale, v_scale, o, m, l, acc) for the fused-int8 tier — the two
    variants are distinct traced programs (the tier is static), so the
    arity switch costs nothing at run time.

    ``lengths[b]`` = keys visible to the LAST query of slot b's block
    (= first query position + T); earlier queries mask one key fewer
    each, which is exactly in-block causality. ``group`` = q heads per
    kv head; q rows are t-major, so row r is query t = r // group.
    """
    if len(rest) == 6:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = rest
    else:
        ks_ref, vs_ref = None, None
        o_ref, m_ref, l_ref, acc_ref = rest
    b = pl.program_id(0)
    j = pl.program_id(1)
    nkv = q_ref.shape[1]
    tg = q_ref.shape[2]
    length = lengths_ref[b]
    num_live = pl.cdiv(length, block_size)  # blocks this slot must visit

    @pl.when(j == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    @pl.when(j < num_live)
    def _fold():
        key_pos = j * block_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_size), 1)
        # Per-query horizon: row r (query t = r // group) sees
        # length - (T - 1 - t) keys; T = tg // group. For T=1 this is
        # the original `key_pos < length` mask.
        t_idx = jax.lax.broadcasted_iota(
            jnp.int32, (tg, 1), 0) // group
        horizon = length - (tg // group - 1) + t_idx      # [tg, 1]
        live = key_pos < horizon                          # [tg, blk]
        for h in range(nkv):  # static unroll: one rank-2 MXU matmul each
            q = q_ref[0, h].astype(jnp.float32)       # [T*G, D]
            k = k_ref[0, :, h].astype(jnp.float32)    # [blk, D]
            v = v_ref[0, :, h].astype(jnp.float32)    # [blk, D]
            if ks_ref is not None:
                # Fused int8 dequant: rows arrived quantized; scale
                # them here, after the DMA. Granule token -> scale
                # column 0 for every head; granule head -> column h.
                si = h % ks_ref.shape[2]
                k = k * ks_ref[0, :, si][:, None]
                v = v * vs_ref[0, :, si][:, None]
            scores = jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale  # [T*G, blk]
            scores = jnp.where(live, scores, _NEG_INF)

            m_prev, l_prev = m_ref[h], l_ref[h]               # [T*G, 1]
            m_new = jnp.maximum(m_prev, scores.max(axis=-1, keepdims=True))
            correction = jnp.exp(m_prev - m_new)
            p = jnp.exp(scores - m_new)                       # [T*G, blk]
            m_ref[h] = m_new
            l_ref[h] = l_prev * correction + p.sum(axis=-1, keepdims=True)
            acc_ref[h] = acc_ref[h] * correction + jnp.dot(
                p, v, preferred_element_type=jnp.float32)     # [T*G, D]

    @pl.when(j == pl.num_programs(1) - 1)
    def _finish():
        o_ref[0] = (acc_ref[:] / jnp.maximum(l_ref[:], 1e-30)
                    ).astype(o_ref.dtype)


def _pack_q(q: jnp.ndarray, nkv: int):
    """[B, T, Nq, D] -> [B, Nkv, T*G, D] (t-major rows per kv head)."""
    b, t, nq, d = q.shape
    g = nq // nkv
    qg = q.reshape(b, t, nkv, g, d)
    return jnp.moveaxis(qg, 1, 2).reshape(b, nkv, t * g, d)


def _unpack_o(o: jnp.ndarray, t: int):
    """[B, Nkv, T*G, D] -> [B, T, Nq, D]."""
    b, nkv, tg, d = o.shape
    g = tg // t
    return jnp.moveaxis(o.reshape(b, nkv, t, g, d), 2, 1) \
        .reshape(b, t, nkv * g, d)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret"))
def decode_attend(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                  lengths: jnp.ndarray, *, block_size: int = 128,
                  k_scale: jnp.ndarray | None = None,
                  v_scale: jnp.ndarray | None = None,
                  interpret: bool | None = None) -> jnp.ndarray:
    """GQA decode attention with block-level length pruning.

    q [B, Nq, D] (the single decode token per slot) or [B, T, Nq, D]
    (a short multi-token block, e.g. the spec-decode verify pass);
    k, v [B, S, Nkv, D] in cache layout; lengths [B] = number of valid
    keys per slot for the block's LAST query (first query position + T;
    for T=1 that is position + 1, unchanged from the single-token
    kernel). Earlier queries in the block see one key fewer each —
    in-block causality. Returns the same rank as ``q``. S must divide
    by block_size (KV-length buckets are powers of two >= 512).

    ``k_scale``/``v_scale`` [B, S, G] select the fused int8-dequant
    tier: k/v are int8 cache rows and dequantize INSIDE the kernel
    after the DMA (per-row scales, granule G = 1 or Nkv).
    """
    single = q.ndim == 3
    if single:
        q = q[:, None]
    b, t, nq, d = q.shape
    s, nkv = k.shape[1], k.shape[2]
    g = nq // nkv
    if s % block_size:
        raise ValueError(f"cache bucket {s} not divisible by {block_size}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nb = s // block_size
    qg = _pack_q(q, nkv)
    lengths = lengths.astype(jnp.int32)
    quantized = k_scale is not None

    def q_index(b_, j, lens):  # noqa: ARG001
        return (b_, 0, 0, 0)

    def kv_index(b_, j, lens):
        # Pruned blocks revisit the slot's last live block — same index
        # as the previous grid step, so no DMA is issued for them.
        num_live = pl.cdiv(lens[b_], block_size)
        return (b_, jnp.minimum(j, num_live - 1), 0, 0)

    def scale_index(b_, j, lens):
        num_live = pl.cdiv(lens[b_], block_size)
        return (b_, jnp.minimum(j, num_live - 1), 0)

    in_specs = [
        pl.BlockSpec((1, nkv, t * g, d), q_index),
        pl.BlockSpec((1, block_size, nkv, d), kv_index),
        pl.BlockSpec((1, block_size, nkv, d), kv_index),
    ]
    operands = [lengths, qg, k, v]
    if quantized:
        kvg = k_scale.shape[-1]
        in_specs += [pl.BlockSpec((1, block_size, kvg), scale_index),
                     pl.BlockSpec((1, block_size, kvg), scale_index)]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nkv, t * g, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((nkv, t * g, 1), jnp.float32),   # running max
            pltpu.VMEM((nkv, t * g, 1), jnp.float32),   # running denom
            pltpu.VMEM((nkv, t * g, d), jnp.float32),   # running numer
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, block_size=block_size,
                          scale=d ** -0.5, group=g),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, t * g, d), q.dtype),
        interpret=interpret,
    )(*operands)
    out = _unpack_o(out, t)
    return out[:, 0] if single else out


def _paged_decode_kernel(lengths_ref, tables_ref, q_ref, k_ref, v_ref,
                         *rest, block_size: int, scale: float,
                         group: int):
    """Identical softmax recurrence to ``_decode_kernel`` — the paged
    variant differs only in WHERE each grid step's K/V block comes
    from (the block-table index map below), so the per-slot length
    pruning and fused dequant carry over unchanged: grid step j of
    slot b masks by the slot's true length and pruned steps elide
    their DMA."""
    _decode_kernel(lengths_ref, q_ref, k_ref, v_ref, *rest,
                   block_size=block_size, scale=scale, group=group)


@functools.partial(jax.jit, static_argnames=("block_size", "interpret"))
def decode_attend_paged(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        lengths: jnp.ndarray, tables: jnp.ndarray, *,
                        block_size: int,
                        k_scale: jnp.ndarray | None = None,
                        v_scale: jnp.ndarray | None = None,
                        interpret: bool | None = None) -> jnp.ndarray:
    """GQA decode attention over a PAGED block pool: the per-slot
    length pruning of ``decode_attend`` extended to walk block lists
    (KV_LAYOUT=paged, docs/KVCACHE.md "Paged tier").

    q [B, Nq, D] or [B, T, Nq, D] (multi-token verify block); k, v are
    the flat device pool [P = num_blocks * block_size, Nkv, D];
    lengths [B] = valid keys per slot for the block's LAST query;
    tables [B, nb] = pool block id holding each slot's logical block
    (nb * block_size is the call's KV bucket). Both scalar operands
    prefetch, so the index map routes each grid step's DMA to
    ``tables[b, j]`` — logically contiguous attention over physically
    scattered blocks, no gather materialisation. Steps past a slot's
    live length revisit its last live block and elide the DMA, exactly
    like the dense kernel.

    ``k_scale``/``v_scale`` [P, G] are the pool's per-block-row scale
    arrays (int8 tier): they ride the SAME block-table index map as
    k/v, so each grid step DMAs its block's scale rows alongside the
    int8 rows and dequantizes in VMEM.
    """
    single = q.ndim == 3
    if single:
        q = q[:, None]
    b, t, nq, d = q.shape
    p, nkv = k.shape[0], k.shape[1]
    g = nq // nkv
    if p % block_size:
        raise ValueError(f"pool rows {p} not divisible by {block_size}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    nb = tables.shape[1]
    kb = k.reshape(p // block_size, block_size, nkv, d)
    vb = v.reshape(p // block_size, block_size, nkv, d)
    qg = _pack_q(q, nkv)
    lengths = lengths.astype(jnp.int32)
    tables = tables.astype(jnp.int32)
    quantized = k_scale is not None

    def q_index(b_, j, lens, tabs):  # noqa: ARG001
        return (b_, 0, 0, 0)

    def kv_index(b_, j, lens, tabs):
        # Walk the slot's block list; pruned steps revisit the last
        # live block (same index as the previous step → no DMA).
        num_live = pl.cdiv(lens[b_], block_size)
        return (tabs[b_, jnp.minimum(j, num_live - 1)], 0, 0, 0)

    def scale_index(b_, j, lens, tabs):
        num_live = pl.cdiv(lens[b_], block_size)
        return (tabs[b_, jnp.minimum(j, num_live - 1)], 0, 0)

    in_specs = [
        pl.BlockSpec((1, nkv, t * g, d), q_index),
        pl.BlockSpec((1, block_size, nkv, d), kv_index),
        pl.BlockSpec((1, block_size, nkv, d), kv_index),
    ]
    operands = [lengths, tables, qg, kb, vb]
    if quantized:
        kvg = k_scale.shape[-1]
        in_specs += [pl.BlockSpec((1, block_size, kvg), scale_index),
                     pl.BlockSpec((1, block_size, kvg), scale_index)]
        operands += [k_scale.reshape(p // block_size, block_size, kvg),
                     v_scale.reshape(p // block_size, block_size, kvg)]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, nb),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, nkv, t * g, d), q_index),
        scratch_shapes=[
            pltpu.VMEM((nkv, t * g, 1), jnp.float32),   # running max
            pltpu.VMEM((nkv, t * g, 1), jnp.float32),   # running denom
            pltpu.VMEM((nkv, t * g, d), jnp.float32),   # running numer
        ],
    )
    out = pl.pallas_call(
        functools.partial(_paged_decode_kernel, block_size=block_size,
                          scale=d ** -0.5, group=g),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, nkv, t * g, d), q.dtype),
        interpret=interpret,
    )(*operands)
    out = _unpack_o(out, t)
    return out[:, 0] if single else out
