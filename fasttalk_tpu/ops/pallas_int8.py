"""Pallas TPU kernels: matmul with int8 weights, dequantized in VMEM.

Why: decode with int8 weight-only quantization should be HBM-bound on
the int8 bytes, but XLA's lowering of ``(x @ q.astype(bf16)) * s``
materialises the converted bf16 weight, so int8 saves almost nothing
(measured on v5e: 4.99 ms/step int8-XLA vs 5.49 bf16 at batch 16 for
the 1B model — a 9% win where bytes promise 45%). These kernels DMA the
int8 tile to VMEM, convert as the MXU consumes it, and scale the small
accumulator instead of the huge weight.

The r2 kernel used a (bk=512, bn=512) 2-D grid whose q-blocks were
*strided* row fragments (512-byte contiguous runs); measured 237 GB/s —
slower in wall time than just streaming bf16. The fix is block shape:
every block here is a run of **whole rows**, so each DMA is one
contiguous span and streams at HBM rate.

Two layouts:
- ``int8_matmul``:  y[M,N] = x[M,K] @ (q[K,N] * s[N]); grid over K row
  blocks of q (contiguous), full N per block, f32 VMEM accumulator.
- ``int8_matmul_t``: y[M,V] = x[M,D] @ (q[V,D] * s[V]).T; grid over V
  row blocks (contiguous), contracting the full D axis per block — the
  tied-embedding lm_head (embed is stored [V, D]) without ever
  materialising the transpose.

Single-device path (like ops/pallas_attention.py): under a TP mesh GSPMD
cannot partition a custom kernel, so the mesh path keeps the XLA matmul.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# jax 0.4.x ships this dataclass as TPUCompilerParams; newer releases
# renamed it. Resolve once so the kernels run on both.
_CompilerParams = getattr(pltpu, "CompilerParams",
                          getattr(pltpu, "TPUCompilerParams", None))

# Per-block VMEM budget for the streamed q block (bytes, int8 elems).
# Double-buffered by the pipeline: 2x this resides in VMEM. XLA's
# scoped-vmem limit DEFAULTS to 16 MiB on this toolchain (measured:
# 8 MiB blocks OOM at 16.84M "limit 16.00M"; nothing in this file sets
# the flag), so 2 MiB blocks leave room for the accumulator/output
# while staying large enough to stream at HBM rate.
_BLOCK_BYTES = 2 * 1024 * 1024
# Working-set ceiling the supports() estimate checks against (blocks
# double-buffered + accumulator + output), a margin under the 16 MiB
# default above; shapes that exceed it (the untied [4096, 128256]
# lm_head's full-N accumulator) fall back to XLA.
_VMEM_BUDGET = 12 * 1024 * 1024


def _row_block(rows: int, cols: int) -> int | None:
    """Largest power-of-two row count dividing ``rows`` whose int8 block
    fits the VMEM budget. Minimum 128: the row count is the x-operand's
    LANE dimension in ``int8_matmul`` (and the output's in
    ``int8_matmul_t``), and Mosaic rejects sub-128 lane tiles
    ("Bad lhs type") — small-K weights fall back to the XLA dequant."""
    b = 1
    while b * 2 <= rows and rows % (b * 2) == 0 \
            and (b * 2) * cols <= _BLOCK_BYTES:
        b *= 2
    return b if rows % b == 0 and b >= 128 else None


def _mm_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, k_blocks: int,
               out_dtype):
    kb = pl.program_id(0)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    w = q_ref[:].astype(x_ref.dtype)  # int8 -> compute dtype, in VMEM
    acc_ref[:] += jax.lax.dot(x_ref[:], w,
                              preferred_element_type=jnp.float32)

    @pl.when(kb == k_blocks - 1)
    def _scale_out():
        scale = s_ref[0].astype(jnp.float32)[None, :]
        o_ref[:] = (acc_ref[:] * scale).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul(x: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray,
                interpret: bool | None = None) -> jnp.ndarray:
    """x [M, K] @ dequant(q [K, N] int8, s [N]) -> [M, N] (x dtype)."""
    m, k = x.shape
    k2, n = q.shape
    assert k == k2 and s.shape == (n,)
    bk = _row_block(k, n)
    assert bk is not None, (k, n)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k_blocks = k // bk

    return pl.pallas_call(
        functools.partial(_mm_kernel, k_blocks=k_blocks, out_dtype=x.dtype),
        grid=(k_blocks,),
        in_specs=[
            pl.BlockSpec((m, bk), lambda kb: (0, kb)),
            pl.BlockSpec((bk, n), lambda kb: (kb, 0)),  # contiguous rows
            pl.BlockSpec((1, n), lambda kb: (0, 0)),
        ],
        out_specs=pl.BlockSpec((m, n), lambda kb: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((m, n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x, q, s.reshape(1, n))


def _mm_t_kernel(x_ref, q_ref, s_ref, o_ref, *, out_dtype):
    w = q_ref[:].astype(x_ref.dtype)  # [bv, D] rows of the embedding
    acc = jax.lax.dot_general(
        x_ref[:], w, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # [M, bv]
    o_ref[:] = (acc * s_ref[0].astype(jnp.float32)[None, :]).astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul_t(x: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray,
                  interpret: bool | None = None) -> jnp.ndarray:
    """x [M, D] @ dequant(q [V, D] int8, s [V]).T -> [M, V] (x dtype).

    The tied-embedding lm_head: q's rows are vocab entries (contiguous),
    contraction runs over the full D axis inside each block, so there is
    no accumulator carry between grid steps.
    """
    m, d = x.shape
    v, d2 = q.shape
    assert d == d2 and s.shape == (v,)
    bv = _row_block(v, d)
    assert bv is not None, (v, d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    return pl.pallas_call(
        functools.partial(_mm_t_kernel, out_dtype=x.dtype),
        grid=(v // bv,),
        in_specs=[
            pl.BlockSpec((m, d), lambda vb: (0, 0)),
            pl.BlockSpec((bv, d), lambda vb: (vb, 0)),  # contiguous rows
            pl.BlockSpec((1, bv), lambda vb: (0, vb)),
        ],
        out_specs=pl.BlockSpec((m, bv), lambda vb: (0, vb)),
        out_shape=jax.ShapeDtypeStruct((m, v), x.dtype),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x, q, s.reshape(1, v))


def supports(x_shape, q_shape, itemsize: int = 2) -> bool:
    """True when the kernel's blocking constraints hold for these shapes.
    ``itemsize``: activation/output element size (2 for bf16, 4 f32)."""
    if len(x_shape) != 2 or len(q_shape) != 2:
        return False
    m = x_shape[0]
    k, n = q_shape
    bk = _row_block(k, n)
    if n % 128 != 0 or bk is None:
        return False
    vmem = 2 * bk * n + 4 * m * n + itemsize * m * (n + k)
    return vmem <= _VMEM_BUDGET


def supports_t(x_shape, q_shape, itemsize: int = 2) -> bool:
    if len(x_shape) != 2 or len(q_shape) != 2:
        return False
    m = x_shape[0]
    v, d = q_shape
    bv = _row_block(v, d)
    if d % 128 != 0 or bv is None:
        return False
    vmem = 2 * bv * d + 2 * itemsize * m * bv + itemsize * m * d
    return vmem <= _VMEM_BUDGET


# ---------------------------------------------------------------------------
# Int4: nibble-packed weights (fasttalk_tpu/quantization/int4.py format),
# unpacked IN-REGISTER per tile so the packed uint8 bytes are what
# crosses HBM — a further 2x byte cut over the int8 kernel above.
# ---------------------------------------------------------------------------


def _row_block4(k: int, n: int, group: int) -> int | None:
    """Unpacked-row block size for the int4 kernel: a multiple of the
    scale group (so each block owns whole groups), >= 128 (lane-dim
    floor, see _row_block), dividing ``k``, with the unpacked int8 tile
    held to half the int8 kernel's block budget — the dequant pipeline
    (unpack -> cast -> scale-multiply) keeps ~2 extra tiles of that
    size live in VMEM."""
    best = None
    b = group
    while b <= k and k % b == 0:
        if b >= 128 and b * n <= _BLOCK_BYTES // 2:
            best = b
        b *= 2
    return best


def _mm4_kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, k_blocks: int,
                group: int, out_dtype):
    kb = pl.program_id(0)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # Unpack two's-complement nibbles: packed row j holds original row
    # 2j in the low nibble, 2j+1 in the high one. int8 ``>>`` is
    # arithmetic, so ``(b << 4) >> 4`` sign-extends the low nibble.
    b = q_ref[:].astype(jnp.int8)  # [bk/2, n] packed pairs
    lo = (b << 4) >> 4
    hi = b >> 4
    bkp, n = b.shape
    w = jnp.stack([lo, hi], axis=1).reshape(2 * bkp, n).astype(x_ref.dtype)
    # Expand group scales [gpb, n] -> [bk, n] with leading-dim-only
    # broadcast+reshape (Mosaic-friendly: lane dim untouched). Group
    # scales vary along K, so the multiply must happen per-tile inside
    # the accumulation — it cannot factor out like the int8 kernel's
    # per-N scale.
    gpb = s_ref.shape[0]
    sexp = jnp.broadcast_to(
        s_ref[:].astype(x_ref.dtype)[:, None, :],
        (gpb, group, n)).reshape(gpb * group, n)
    acc_ref[:] += jax.lax.dot(x_ref[:], w * sexp,
                              preferred_element_type=jnp.float32)

    @pl.when(kb == k_blocks - 1)
    def _out():
        o_ref[:] = acc_ref[:].astype(out_dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int4_matmul(x: jnp.ndarray, q4: jnp.ndarray, s: jnp.ndarray,
                interpret: bool | None = None) -> jnp.ndarray:
    """x [M, K] @ dequant(q4 [K/2, N] packed int4, s [K/G, N]) -> [M, N]."""
    m, k = x.shape
    kp, n = q4.shape
    assert k == 2 * kp, (k, kp)
    groups = s.shape[0]
    assert s.shape == (groups, n) and k % groups == 0
    group = k // groups
    bk = _row_block4(k, n, group)
    assert bk is not None, (k, n, group)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k_blocks = k // bk

    return pl.pallas_call(
        functools.partial(_mm4_kernel, k_blocks=k_blocks, group=group,
                          out_dtype=x.dtype),
        grid=(k_blocks,),
        in_specs=[
            pl.BlockSpec((m, bk), lambda kb: (0, kb)),
            pl.BlockSpec((bk // 2, n), lambda kb: (kb, 0)),  # contiguous rows
            pl.BlockSpec((bk // group, n), lambda kb: (kb, 0)),
        ],
        out_specs=pl.BlockSpec((m, n), lambda kb: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((m, n), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary",)),
        interpret=interpret,
    )(x, q4, s)


def supports_q4(x_shape, q4_shape, s_shape, itemsize: int = 2) -> bool:
    """True when the int4 kernel's blocking constraints hold."""
    if len(x_shape) != 2 or len(q4_shape) != 2 or len(s_shape) != 2:
        return False
    m = x_shape[0]
    kp, n = q4_shape
    k = 2 * kp
    groups = s_shape[0]
    if s_shape[1] != n or groups <= 0 or k % groups:
        return False
    group = k // groups
    bk = _row_block4(k, n, group)
    if n % 128 != 0 or bk is None:
        return False
    # Packed block double-buffered (bk//2 * n * 2 = bk*n) + unpacked
    # int8 + dequantized/scaled tiles + accumulator + x + out.
    vmem = (2 + 2 * itemsize) * bk * n + 4 * m * n + itemsize * m * (n + k)
    return vmem <= _VMEM_BUDGET
