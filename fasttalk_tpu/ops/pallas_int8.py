"""Pallas TPU kernel: matmul with int8 weights, dequantized in-kernel.

Why: decode with int8 weight-only quantization should be HBM-bound on
the int8 bytes, but the XLA lowering of ``(x @ q.astype(bf16)) * s``
re-materialises the converted weight tile on the VPU every scan step —
measured on a v5e-1 this cost int8 ~30% of its aggregate throughput
advantage (README perf table). Here the int8 tile is DMA'd to VMEM,
converted once in registers as the MXU consumes it, and the per-output-
channel scale is applied to the (tiny) accumulator instead of the (huge)
weight.

Shapes: y[M, N] = x[M, K] @ (q[K, N] * s[N]); M is the decode batch
(num_slots — small), K/N are model dims. Grid (N/bn, K/bk) with the K
axis innermost, accumulating in an f32 VMEM scratch; the scale multiply
happens once at the last K block. M stays unblocked (a whole-axis block
is always legal), so any slot count works.

Single-device path (like ops/pallas_attention.py): under a TP mesh GSPMD
cannot partition a custom kernel, so the mesh path keeps the XLA matmul.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _kernel(x_ref, q_ref, s_ref, o_ref, acc_ref, *, k_blocks: int,
            out_dtype):
    kb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    w = q_ref[:].astype(x_ref.dtype)  # int8 -> compute dtype, in VMEM
    acc_ref[:] += jax.lax.dot(x_ref[:], w,
                              preferred_element_type=jnp.float32)

    @pl.when(kb == k_blocks - 1)
    def _scale_out():
        scale = s_ref[0].astype(jnp.float32)[None, :]
        o_ref[:] = (acc_ref[:] * scale).astype(out_dtype)


def _pick_block(dim: int, candidates: tuple[int, ...]) -> int | None:
    for c in candidates:
        if dim % c == 0:
            return c
    return None


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_matmul(x: jnp.ndarray, q: jnp.ndarray, s: jnp.ndarray,
                interpret: bool | None = None) -> jnp.ndarray:
    """x [M, K] @ dequant(q [K, N] int8, s [N]) -> [M, K dtype, N]."""
    m, k = x.shape
    k2, n = q.shape
    assert k == k2 and s.shape == (n,)
    bk = _pick_block(k, (512, 256, 128))
    bn = _pick_block(n, (512, 256, 128))
    assert bk is not None and bn is not None, (k, n)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    k_blocks = k // bk

    return pl.pallas_call(
        functools.partial(_kernel, k_blocks=k_blocks, out_dtype=x.dtype),
        grid=(n // bn, k_blocks),
        in_specs=[
            pl.BlockSpec((m, bk), lambda nb, kb: (0, kb)),
            pl.BlockSpec((bk, bn), lambda nb, kb: (kb, nb)),
            pl.BlockSpec((1, bn), lambda nb, kb: (0, nb)),
        ],
        out_specs=pl.BlockSpec((m, bn), lambda nb, kb: (0, nb)),
        out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
        scratch_shapes=[pltpu.VMEM((m, bn), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(x, q, s.reshape(1, n))


def supports(x_shape, q_shape) -> bool:
    """True when the kernel's blocking constraints hold for these shapes."""
    if len(x_shape) != 2 or len(q_shape) != 2:
        return False
    k, n = q_shape
    return _pick_block(k, (512, 256, 128)) is not None \
        and _pick_block(n, (512, 256, 128)) is not None
