"""Int8 weight-only quantization for serving.

The reference's highest-throughput config served an AWQ-INT4 checkpoint
inside vLLM (reference: docker-compose.vllm.yml:38-41,
.env.vllm.example:21 — quantization lived entirely in the external
engine). Here the equivalent lives in-tree: per-output-channel symmetric
int8 for every matmul weight. Decode on TPU is HBM-bandwidth-bound, so
halving weight bytes (bf16 → int8 + one scale row) is a direct
throughput lever; the dequantize (a convert + broadcast multiply) fuses
into the matmul's operand read, so the int8 bytes are what crosses HBM.

Format: a quantized leaf is the dict ``{"q": int8[..., in, out],
"s": float32[..., out]}`` in place of the original array — pytree
structure stays self-describing, and parallel/sharding.py names rules
for the "q"/"s" leaves so tensor parallelism works unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Any

import jax
import jax.numpy as jnp

# Matmul weights quantized per OUTPUT channel (scale over the
# contraction axis). Norms/biases stay bf16 (tiny).
QUANTIZED_LEAVES = frozenset(
    {"wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down", "lm_head"})
# The embedding quantizes per ROW (one scale per vocab entry): rows are
# gathered for input embedding (dequant of the few looked-up rows is
# free) and are the output channels of the tied lm_head matmul — for
# Llama-3.2 1B/3B that matmul reads 525 MB bf16 per decode step, ~18%
# of the whole step (VERDICT r2 weak #1); int8 halves it.
EMBED_LEAF = "embed"


def quantize_math_out(wf: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-output-channel symmetric int8 math (scale over axis -2).
    THE single definition — loader random-init reuses it so generated
    and quantize_params-produced tables can never diverge."""
    s = jnp.maximum(jnp.max(jnp.abs(wf), axis=-2) / 127.0, 1e-8)
    return jnp.round(wf / s[..., None, :]).astype(jnp.int8), s


def quantize_math_row(wf: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Per-row symmetric int8 math (scale over axis -1; the embedding)."""
    s = jnp.maximum(jnp.max(jnp.abs(wf), axis=-1) / 127.0, 1e-8)
    return jnp.round(wf / s[..., None]).astype(jnp.int8), s


@partial(jax.jit, donate_argnums=(0,))
def _quantize_leaf(w: jax.Array) -> dict[str, jax.Array]:
    """Per-output-channel symmetric int8.

    Weights are [..., in, out] (stacked layer axis first for the scanned
    transformer body); the scale reduces over the contraction axis only,
    giving one scale per (layer, output channel).
    """
    q, s = quantize_math_out(w.astype(jnp.float32))
    return {"q": q, "s": s}


@partial(jax.jit, donate_argnums=(0,))
def _quantize_embed(w: jax.Array) -> dict[str, jax.Array]:
    """Per-row symmetric int8 for the embedding table [V, D]."""
    q, s = quantize_math_row(w.astype(jnp.float32))
    return {"q": q, "s": s}


@partial(jax.jit, donate_argnums=(0,))
def _quantize_head_t(w: jax.Array) -> dict[str, jax.Array]:
    """The untied lm_head [D, V], stored TRANSPOSED: ``{"qt": int8[V, D],
    "s": f32[V]}``. Scale math is identical to per-output-channel on
    [D, V] (the max runs over D either way), so this is a pure layout
    change — but it is the layout the contiguous row-block kernel
    (ops/pallas_int8.py int8_matmul_t) can stream: the [D, V] layout
    needs a full-V f32 accumulator that busts VMEM, which silently sent
    large-vocab untied heads back to the XLA dequant path on the single
    biggest decode matmul (ADVICE r3)."""
    q, s = quantize_math_row(w.T.astype(jnp.float32))
    return {"qt": q, "s": s}


def quantize_params(params: Any) -> Any:
    """Quantize the matmul weights of a (possibly sharded) param pytree.

    Runs leaf-by-leaf on device with donation, so each bf16 weight is
    freed as its int8 replacement is built — peak memory is one leaf,
    not a full second copy. Under a mesh, GSPMD keeps each result in the
    shards of its input (the per-channel max over a TP-sharded
    contraction axis lowers to a local max + all-reduce-max over ICI).
    """
    out = dict(params)
    out["layers"] = dict(params["layers"])
    for name in list(out["layers"]):
        if name in QUANTIZED_LEAVES:
            out["layers"][name] = _quantize_leaf(out["layers"][name])
    if "lm_head" in out:
        out["lm_head"] = _quantize_head_t(out["lm_head"])
    out["embed"] = _quantize_embed(out["embed"])
    return out


def matmul(x: jax.Array, w: Any, pallas_ok: bool = False,
           pallas_int4: bool = False) -> jax.Array:
    """``x @ w`` for a plain or quantized weight leaf.

    For int8 weights the convert happens inside the matmul; with
    ``pallas_ok`` (single-device decode, T=1) the Pallas kernel
    (ops/pallas_int8.py) converts tile-by-tile in VMEM and scales the
    accumulator, avoiding XLA's per-step weight re-materialisation.
    Int4 leaves (``{"q4", "s"}``, fasttalk_tpu/quantization/) dequantize
    in the operand read: nibble unpack → int8 → x.dtype × group scales,
    never a full f32 weight; ``pallas_int4`` (TPU_USE_PALLAS_INT4)
    routes T=1 decode to the in-register unpacking kernel instead.
    """
    if isinstance(w, dict):
        if "q4" in w:
            if pallas_int4 and x.ndim == 3 and x.shape[1] == 1:
                from fasttalk_tpu.ops.pallas_int8 import (int4_matmul,
                                                          supports_q4)

                if supports_q4((x.shape[0], x.shape[2]), w["q4"].shape,
                               w["s"].shape, jnp.dtype(x.dtype).itemsize):
                    return int4_matmul(x[:, 0], w["q4"], w["s"])[:, None]
            from fasttalk_tpu.quantization.int4 import unpack_int4

            group = (2 * w["q4"].shape[-2]) // w["s"].shape[-2]
            wd = unpack_int4(w["q4"]).astype(x.dtype)
            wd = wd * jnp.repeat(w["s"].astype(x.dtype), group, axis=-2)
            return x @ wd
        if "qt" in w:
            # Transposed untied lm_head {"qt": [V, D], "s": [V]}: the
            # same contiguous row-block kernel as the tied embedding
            # streams it at HBM rate (ADVICE r3 — the [D, V] layout's
            # full-V accumulator busted VMEM and forced XLA dequant).
            if pallas_ok and x.ndim == 3 and x.shape[1] == 1:
                from fasttalk_tpu.ops.pallas_int8 import (int8_matmul_t,
                                                          supports_t)

                if supports_t((x.shape[0], x.shape[2]), w["qt"].shape,
                              jnp.dtype(x.dtype).itemsize):
                    return int8_matmul_t(x[:, 0], w["qt"], w["s"])[:, None]
            out = jax.lax.dot_general(
                x, w["qt"].astype(x.dtype),
                (((x.ndim - 1,), (1,)), ((), ())))
            return out * w["s"].astype(x.dtype)
        if pallas_ok and x.ndim == 3 and x.shape[1] == 1:
            from fasttalk_tpu.ops.pallas_int8 import int8_matmul, supports

            if supports((x.shape[0], x.shape[2]), w["q"].shape,
                        jnp.dtype(x.dtype).itemsize):
                return int8_matmul(x[:, 0], w["q"], w["s"])[:, None]
        return (x @ w["q"].astype(x.dtype)) * w["s"].astype(x.dtype)
    return x @ w


def embed_lookup(emb: Any, tokens: jax.Array, dtype: Any) -> jax.Array:
    """Input-embedding gather for a plain or row-quantized table."""
    if isinstance(emb, dict):
        rows = jnp.take(emb["q"], tokens, axis=0).astype(jnp.float32)
        s = jnp.take(emb["s"], tokens, axis=0)
        return (rows * s[..., None]).astype(dtype)
    return jnp.take(emb, tokens, axis=0)


def matmul_tied(x: jax.Array, emb: Any, pallas_ok: bool = False) -> jax.Array:
    """``x @ embed.T`` — the tied-embedding lm_head ([.., D] @ [V, D].T).

    For a row-quantized table the per-row scale is the per-output-column
    scale of the transposed matmul; with ``pallas_ok`` the contiguous
    row-block kernel streams the int8 table without materialising the
    transpose (ops/pallas_int8.py int8_matmul_t).
    """
    if isinstance(emb, dict):
        if pallas_ok and x.ndim == 3 and x.shape[1] == 1:
            from fasttalk_tpu.ops.pallas_int8 import (int8_matmul_t,
                                                      supports_t)

            if supports_t((x.shape[0], x.shape[2]), emb["q"].shape,
                          jnp.dtype(x.dtype).itemsize):
                return int8_matmul_t(x[:, 0], emb["q"], emb["s"])[:, None]
        return (x @ emb["q"].astype(x.dtype).T) * emb["s"].astype(x.dtype)
    return x @ emb.T


def is_quantized(params: Any) -> bool:
    return isinstance(params.get("layers", {}).get("wq"), dict)


def quantizing_put(inner_put, raw_put):
    """Wrap a loader ``put(host_array, path)`` hook so each matmul weight
    is quantized on the host *before* placement — device HBM never holds
    the bf16 copy, so a 70B int8 load peaks at int8 bytes per chip (the
    post-hoc quantize_params path peaks at the full bf16 footprint).

    ``inner_put`` places unquantized leaves (with the engine dtype cast);
    ``raw_put`` places q/s without casting (q stays int8, s float32).
    """
    import numpy as np

    def put(arr, path: str):
        name = path.split("/")[-1]
        a = np.asarray(arr)
        if name == "lm_head" and a.ndim == 2:
            # Untied head stored transposed (see _quantize_head_t).
            # ``a`` arrives [D, V] — the loader's ``.T`` view of the
            # [V, D] tensor safetensors delivered — so quantize in
            # column blocks straight off that view: peak extra host
            # memory is one small f32 block, not a full contiguous f32
            # transpose of a 128k-vocab head (~2 GB for 8B).
            d, v = a.shape
            q = np.empty((v, d), np.int8)
            s = np.empty((v,), np.float32)
            step = max(1, (4 << 20) // max(1, d))  # ~16 MB f32 blocks
            for j in range(0, v, step):
                blk = np.asarray(a[:, j:j + step], np.float32)
                sb = np.maximum(np.max(np.abs(blk), axis=0) / 127.0,
                                1e-8)
                q[j:j + step] = np.round(blk / sb[None, :]).astype(
                    np.int8).T
                s[j:j + step] = sb
            return {"qt": raw_put(q, f"{path}/qt"),
                    "s": raw_put(s, f"{path}/s")}
        if name == EMBED_LEAF and a.ndim == 2:
            s = np.maximum(
                np.max(np.abs(a.astype(np.float32)), axis=-1) / 127.0, 1e-8)
            q = np.round(a / s[..., None]).astype(np.int8)
            return {"q": raw_put(q, f"{path}/q"),
                    "s": raw_put(s.astype(np.float32), f"{path}/s")}
        if name in QUANTIZED_LEAVES and a.ndim >= 2:
            s = np.max(np.abs(a.astype(np.float32)), axis=-2) / 127.0
            s = np.maximum(s, 1e-8)
            q = np.round(a / s[..., None, :]).astype(np.int8)
            return {"q": raw_put(q, f"{path}/q"),
                    "s": raw_put(s.astype(np.float32), f"{path}/s")}
        return inner_put(arr, path)

    return put
