"""Grouped-query attention over a preallocated KV cache.

Two XLA paths tuned for the two phases of serving:

- ``attend`` — direct full-softmax attention, used for decode (T=1 per
  slot): the score tensor is tiny, XLA fuses QK^T → softmax → PV into a
  few MXU calls.
- ``attend_blockwise`` — flash-style online-softmax scan over key blocks,
  used for prefill chunks: bounds the score tensor to
  [B, T, heads, block] regardless of cache length, so an 8k-context
  prefill never materialises an O(T·S) buffer in HBM.

Both mask by absolute position: key j is visible to query at absolute
position p iff j <= p, which simultaneously enforces causality within a
chunk and hides unwritten/garbage cache tail.

A Pallas kernel with per-slot true lengths lives in
``fasttalk_tpu.ops.pallas_attention`` and can replace ``attend`` on TPU
(config: TPU_USE_PALLAS_ATTENTION).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

_NEG_INF = -1e30


def paged_gather_indices(block_table: jnp.ndarray,
                         block_size: int) -> jnp.ndarray:
    """Flat pool-row indices for a paged attention read.

    ``block_table`` [B, nb] maps each slot's logical block ``i`` to a
    pool block id; the result [B, nb * block_size] names the pool row
    holding every logical position ``0..nb*block_size`` per slot, in
    position order — so a gather through it yields a contiguous-looking
    [B, S, ...] key/value region the position-masked ``attend`` paths
    consume unchanged. This is the XLA *gather fallback* of the paged
    KV tier (KV_LAYOUT=paged, docs/KVCACHE.md): it runs everywhere the
    dense tier does; the block-walking Pallas kernel
    (ops/pallas_attention.decode_attend_paged) is the TPU fast path.
    Unallocated table entries may be any in-range id (conventionally
    0): their rows sit beyond every query's position mask.
    """
    b, nb = block_table.shape
    idx = (block_table[:, :, None] * block_size
           + jnp.arange(block_size, dtype=block_table.dtype)[None, None, :])
    return idx.reshape(b, nb * block_size)


def gather_paged_rows(pool_rows: jnp.ndarray,
                      flat_idx: jnp.ndarray) -> jnp.ndarray:
    """Gather one layer's paged KV rows: pool [P, ...] × indices
    [B, S] → [B, S, ...]. Plain fancy indexing so XLA lowers it to one
    gather feeding the attention contraction."""
    return pool_rows[flat_idx]


def _split_gqa(q: jnp.ndarray, num_kv_heads: int) -> jnp.ndarray:
    """[B, T, Nq, D] -> [B, T, Nkv, G, D]."""
    b, t, nq, d = q.shape
    return q.reshape(b, t, num_kv_heads, nq // num_kv_heads, d)


def attend(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
           q_positions: jnp.ndarray) -> jnp.ndarray:
    """Full-softmax GQA. q [B,T,Nq,D]; k,v [B,S,Nkv,D]; q_positions [B,T]."""
    nkv = k.shape[2]
    scale = q.shape[-1] ** -0.5
    qg = _split_gqa(q, nkv)
    scores = jnp.einsum("btkgd,bskd->btkgs", qg, k,
                        preferred_element_type=jnp.float32) * scale
    key_pos = jnp.arange(k.shape[1])
    mask = key_pos[None, None, :] <= q_positions[:, :, None]  # [B,T,S]
    scores = jnp.where(mask[:, :, None, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("btkgs,bskd->btkgd", probs.astype(v.dtype), v)
    b, t = q.shape[:2]
    return out.reshape(b, t, q.shape[2], q.shape[3])


def online_softmax_fold(qg: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                        q_positions: jnp.ndarray, key_pos: jnp.ndarray,
                        carry: tuple) -> tuple:
    """Fold one K/V block into flash-attention online-softmax state.

    The single source of the numerics-critical recurrence, shared by
    ``attend_blockwise`` (local key blocks) and
    ``parallel.ring_attention`` (blocks visiting over ICI).

    qg [B, Tq, K, G, D] float32; k/v [B, Tk, K, D] any dtype;
    q_positions [B, Tq] and key_pos [Tk] are absolute positions;
    carry = (m [B,Tq,K,G], l [B,Tq,K,G], acc [B,Tq,K,G,D]), all float32.
    """
    m, l, acc = carry
    scale = qg.shape[-1] ** -0.5
    scores = jnp.einsum("btkgd,bskd->btkgs", qg, k.astype(jnp.float32)) * scale
    mask = key_pos[None, None, :] <= q_positions[:, :, None]  # [B, Tq, Tk]
    scores = jnp.where(mask[:, :, None, None, :], scores, _NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    correction = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * correction + p.sum(axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        "btkgs,bskd->btkgd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def fold_init(b: int, t: int, nkv: int, g: int, d: int) -> tuple:
    """Initial (m, l, acc) state for ``online_softmax_fold``."""
    return (
        jnp.full((b, t, nkv, g), _NEG_INF, jnp.float32),
        jnp.zeros((b, t, nkv, g), jnp.float32),
        jnp.zeros((b, t, nkv, g, d), jnp.float32),
    )


def fold_finish(carry: tuple, out_dtype) -> jnp.ndarray:
    """Normalise the accumulated state into [B, T, Nq, D] output."""
    _, l, acc = carry
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    b, t, nkv, g, d = acc.shape
    return out.reshape(b, t, nkv * g, d).astype(out_dtype)


def attend_blockwise(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
                     q_positions: jnp.ndarray, block_size: int = 512
                     ) -> jnp.ndarray:
    """Online-softmax GQA over key blocks (flash-attention recurrence)."""
    b, t, nq, d = q.shape
    s, nkv = k.shape[1], k.shape[2]
    block_size = min(block_size, s)
    if s % block_size:
        raise ValueError(f"cache length {s} not divisible by block {block_size}")
    nblocks = s // block_size
    qg = _split_gqa(q, nkv).astype(jnp.float32)

    kb = k.reshape(b, nblocks, block_size, nkv, d)
    vb = v.reshape(b, nblocks, block_size, nkv, d)
    kb = jnp.moveaxis(kb, 1, 0)  # [N, B, blk, Nkv, D]
    vb = jnp.moveaxis(vb, 1, 0)
    block_offsets = jnp.arange(nblocks) * block_size

    def step(carry, xs):
        kblk, vblk, off = xs
        key_pos = off + jnp.arange(block_size)
        return online_softmax_fold(qg, kblk, vblk, q_positions, key_pos,
                                   carry), None

    g = nq // nkv
    carry, _ = jax.lax.scan(step, fold_init(b, t, nkv, g, d),
                            (kb, vb, block_offsets))
    return fold_finish(carry, q.dtype)
