"""Int8 KV-cache quantization: per-row symmetric scales.

The weight tier (ops/quant.py) halves what decode reads of the model;
this module halves what decode reads of the *cache* — the dominant HBM
consumer at scale, and the thing every attention read is bound on. The
scheme is KIVI/vLLM-fp8-style per-token scaling: each freshly computed
K/V row is quantized at write time with a max-abs scale over its own
values, and the attention matmuls dequantize on read (a convert + one
broadcast multiply that XLA fuses into the operand load, exactly like
the int8 weight path) — so int8 bytes are what crosses HBM and the
full-precision cache is never materialised.

Scale granularity (``KV_QUANT_GRANULE``):

- ``"token"`` (default): one float32 scale per (layer, slot, position)
  row — max-abs over the whole [Kv, H] row. Cheapest (4 bytes per
  2·Kv·H int8 bytes) and the KIVI per-token baseline.
- ``"head"``: one scale per (layer, slot, position, kv-head) — max-abs
  over [H] only. Tighter when head magnitudes diverge, at Kv× the
  scale storage (still tiny next to the rows).

Both store scales as a trailing granule axis ``G`` (1 or num_kv_heads),
so every consumer broadcasts uniformly: ``q * s[..., None]`` covers
either shape against a [..., Kv, H] row block.

Storage layout (models/llama.py ``KVCache``): ``k``/``v`` int8
[L, B, S, Kv, H] plus ``k_scale``/``v_scale`` float32 [L, B, S, G].
Everything that moves KV — the decode scatter, the three prefill
paths, the cross-slot shared-prefix copy, and the host offload tier's
park/restore — moves rows *and* scales together in the quantized
domain, so HBM, PCIe and host-RAM all hold int8+scales bytes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Below this magnitude a row is effectively zero; the floor keeps the
# divide finite and quantizes such rows to exact zeros (matching the
# bf16 cache's zero-initialised, never-attended tail).
_EPS = 1e-8

GRANULES = ("token", "head")


def granule_dim(granule: str, num_kv_heads: int) -> int:
    """Scale-axis length G for a granule name (see module docstring)."""
    if granule not in GRANULES:
        raise ValueError(f"KV_QUANT_GRANULE must be one of {GRANULES}, "
                         f"got {granule!r}")
    return num_kv_heads if granule == "head" else 1


def kv_quantize(x: jax.Array, g: int) -> tuple[jax.Array, jax.Array]:
    """Quantize K/V rows ``x`` [..., Kv, H] → (int8 rows, f32 scales
    [..., G]) with symmetric per-row max-abs scales. ``g`` is the
    granule axis length: 1 (per token row) or Kv (per head row)."""
    xf = x.astype(jnp.float32)
    if g == 1:
        amax = jnp.max(jnp.abs(xf), axis=(-2, -1))[..., None]
    else:
        amax = jnp.max(jnp.abs(xf), axis=-1)
    s = jnp.maximum(amax / 127.0, _EPS)
    q = jnp.clip(jnp.round(xf / s[..., None]), -127, 127)
    return q.astype(jnp.int8), s


def kv_dequantize(q: jax.Array, s: jax.Array, dtype) -> jax.Array:
    """int8 rows [..., Kv, H] × scales [..., G] → ``dtype`` rows.

    Written so XLA fuses the convert+multiply into the consuming
    matmul's operand read — callers pass the result straight into the
    attention einsum and the int8 bytes are what leaves HBM."""
    return (q.astype(jnp.float32) * s[..., None]).astype(dtype)
