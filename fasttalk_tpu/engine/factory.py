"""Engine construction from service Config.

The device branch the reference routed through _detect_compute_device
(reference: app/utils/config.py:17-60) plus provider selection
(websocket_server_vllm.py:74-138) collapse here into one factory: the
``tpu`` provider builds the in-tree JAX engine on whatever platform JAX
has (TPU in production, CPU in tests); ``fake`` builds the test engine.
"""

from __future__ import annotations

import jax.numpy as jnp

from fasttalk_tpu.engine.engine import EngineBase, TPUEngine
from fasttalk_tpu.engine.fake import FakeEngine
from fasttalk_tpu.engine.tokenizer import load_tokenizer
from fasttalk_tpu.models.configs import get_model_config
from fasttalk_tpu.models.loader import find_checkpoint_dir, load_params
from fasttalk_tpu.utils.config import Config
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("engine.factory")

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
           "float16": jnp.float16}


def weight_bytes_by_tier(m, dsize: int, tp: int = 1,
                         group: int = 128) -> dict:
    """Per-device weight bytes for each WEIGHT_QUANT tier — the one
    place the weight-footprint math lives (budget check, overflow
    remedies, BENCH_MODE=int4 envelopes, tests).

    Sharding facts encoded (parallel/sharding.py): norm scales
    replicate; matmuls/embedding shard over "tp"; every quantized
    tensor gains float32 scales, counted replicated (conservative —
    they are KiB-to-half-MiB scale).
    """
    norm_params = (2 * m.num_layers + 1) * m.hidden_size
    # The seven stacked layer matmuls (quantization/int4.py INT4_LEAVES).
    matmul_per_layer = (m.hidden_size * m.q_dim
                        + 2 * m.hidden_size * m.kv_dim
                        + m.q_dim * m.hidden_size
                        + 3 * m.hidden_size * m.intermediate_size)
    scales_per_layer = (m.q_dim + 2 * m.kv_dim + m.hidden_size
                        + 2 * m.intermediate_size + m.hidden_size)
    matmul = m.num_layers * matmul_per_layer
    scales8 = m.num_layers * scales_per_layer
    # Embedding (and untied lm_head) quantize per ROW at int8 in both
    # quantized tiers — the gather and the streaming head kernel want
    # per-row scales (quantization/__init__.py).
    table = m.hidden_size * m.vocab_size
    tscales = m.vocab_size
    if not m.tie_embeddings:
        table += m.hidden_size * m.vocab_size
        tscales += m.vocab_size
    other = m.param_count() - matmul - table - norm_params  # qkv biases
    return {
        "off": ((m.param_count() - norm_params) * dsize // tp
                + norm_params * dsize),
        "int8": ((matmul + table) // tp + other * dsize // tp
                 + (scales8 + tscales) * 4 + norm_params * dsize),
        # int4: two matmul weights per byte + one f32 scale per
        # (group x out-channel); table stays int8 per-row.
        "int4": (matmul // 2 // tp + (matmul // group) * 4
                 + table // tp + tscales * 4
                 + other * dsize // tp + norm_params * dsize),
    }


def _effective_weight_quant(cfg: Config) -> str:
    """The weight tier the build will actually run. Config resolves
    WEIGHT_QUANT and the legacy TPU_QUANTIZE alias at construction,
    but callers that assign ``cfg.quantize`` AFTER construction
    (tests, scripts predating the weight_quant knob) bypass
    __post_init__ — honor the legacy attr the way the pre-int4
    factory did."""
    legacy = "off" if cfg.quantize in ("", "none", "off") else cfg.quantize
    if cfg.weight_quant == "off" and legacy != "off":
        return legacy
    return cfg.weight_quant


def check_hbm_budget(model_cfg, cfg: Config, dtype, n_devices: int) -> dict:
    """Account weights + KV cache against the HBM budget before any
    allocation, so a bad TPU_DECODE_SLOTS / TPU_MAX_MODEL_LEN fails with
    a named message instead of an opaque device OOM mid-load. Wires the
    TPU_HBM_UTILIZATION knob the way the reference never wired its
    VLLM_GPU_MEMORY_UTILIZATION passthrough (reference:
    .env.vllm.example:40 — forwarded to the external container, no
    in-tree accounting).

    Returns the accounting dict (bytes, per device); raises ValueError
    when over budget. Skips silently when the backend exposes no memory
    stats (CPU tests).

    Sharding facts the math encodes (parallel/sharding.py): weights
    shard over "tp" only (each dp replica holds a full copy); the KV
    cache shards over both "tp" (kv heads) and "dp" (slots). Int8
    weights count int8 bytes because quantization happens host-side
    before placement (ops/quant.py quantizing_put) — HBM never holds
    the bf16 copy.
    """
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        limit = stats.get("bytes_limit")
    except Exception:
        limit = None
    dsize = jnp.dtype(dtype).itemsize
    tp = max(1, cfg.tp_size)
    m = model_cfg
    # The per-tier footprint math lives in weight_bytes_by_tier (norm
    # scales replicated, matmuls/embedding sharded over "tp", f32
    # scales counted replicated).
    weight_quant = _effective_weight_quant(cfg)
    tiers = weight_bytes_by_tier(m, dsize, tp=tp,
                                 group=cfg.weight_quant_group)
    wbytes_dev = tiers.get(weight_quant, tiers["off"])
    if cfg.kv_quant == "int8":
        # Quantized KV tier (ops/kv_quant.py): int8 rows + per-row
        # float32 scales — the accounting sees honest quantized bytes,
        # so the same HBM budget admits ~2x the slots x context.
        from fasttalk_tpu.ops.kv_quant import granule_dim

        g = granule_dim(cfg.kv_quant_granule, m.num_kv_heads)
        kv_row_bytes = 2 * (m.num_kv_heads * m.head_dim * 1 + g * 4)
    else:
        kv_row_bytes = m.num_kv_heads * m.head_dim * 2 * dsize
    kv_row_bytes *= m.num_layers  # one logical token row, all layers
    dense_rows = cfg.decode_slots * cfg.max_model_len
    paged = cfg.kv_layout == "paged"
    pool_blocks = 0
    if paged:
        # Paged tier (kvcache/blocks.py): HBM is accounted by POOL
        # BLOCKS, not slots x max_len. KV_POOL_BLOCKS=0 asks for the
        # dense-equivalent pool, then SHRINKS to what the budget
        # actually holds — this fit-to-budget step is exactly where
        # the paged layout admits mixed-context fleets the dense
        # layout rejects outright.
        pool_blocks = cfg.kv_pool_blocks \
            or dense_rows // cfg.kv_block_size
        kv = pool_blocks * cfg.kv_block_size * kv_row_bytes
    else:
        kv = dense_rows * kv_row_bytes
    acct = {
        "weight_bytes_per_device": wbytes_dev,
        "kv_cache_bytes_per_device": kv // n_devices,
        "hbm_limit_bytes": limit,
        "hbm_utilization": cfg.hbm_util,
        "kv_pool_blocks": pool_blocks,
    }
    if limit:
        budget = limit * cfg.hbm_util
        kv_budget = budget - acct["weight_bytes_per_device"]
        block_bytes = cfg.kv_block_size * kv_row_bytes
        fit_blocks = max(0, int(kv_budget // block_bytes))
        if paged and not cfg.kv_pool_blocks:
            # Auto pool: fit to the budget, floored at one full
            # max_len context (below that nothing long can ever run
            # and the layout cannot help).
            floor = -(-cfg.max_model_len // cfg.kv_block_size)
            if fit_blocks < floor:
                raise ValueError(
                    f"KV_LAYOUT=paged: the HBM budget holds only "
                    f"{fit_blocks} KV blocks of {cfg.kv_block_size} "
                    f"tokens after {wbytes_dev / 2**30:.2f} GiB of "
                    f"weights, below the {floor} blocks one "
                    f"TPU_MAX_MODEL_LEN={cfg.max_model_len} context "
                    "needs. Lower TPU_MAX_MODEL_LEN, enable "
                    "KV_QUANT=int8, or raise TPU_HBM_UTILIZATION.")
            pool_blocks = min(pool_blocks, fit_blocks)
            acct["kv_pool_blocks"] = pool_blocks
            acct["kv_cache_bytes_per_device"] = \
                pool_blocks * block_bytes // n_devices
        need = (acct["weight_bytes_per_device"]
                + acct["kv_cache_bytes_per_device"])
        if need > budget:
            # The blocks-available math, and the remedy that actually
            # changes the admission model — not just smaller numbers
            # for the same dense layout. Always show the weight-bytes
            # math per tier: quartering weight bytes is the other
            # first-order lever, and the reader should see what each
            # tier would cost on THEIR model before retuning KV knobs.
            tier_math = (
                f"Weight bytes/device by tier ("
                f"WEIGHT_QUANT={weight_quant}): "
                f"off(bf16)={tiers['off'] / 2**30:.2f} GiB, "
                f"int8={tiers['int8'] / 2**30:.2f} GiB, "
                f"int4+scales={tiers['int4'] / 2**30:.2f} GiB "
                f"(group={cfg.weight_quant_group}).")
            if paged:
                remedy = (
                    f"Lower KV_POOL_BLOCKS ({pool_blocks}; 0 = "
                    "fit-to-budget), KV_BLOCK_SIZE "
                    f"({cfg.kv_block_size}), or TPU_MAX_MODEL_LEN "
                    f"({cfg.max_model_len}); enable WEIGHT_QUANT=int4 "
                    "/ KV_QUANT=int8; or raise TPU_HBM_UTILIZATION. "
                    + tier_math)
            else:
                dense_blocks = dense_rows // cfg.kv_block_size
                remedy = (
                    f"The dense layout preallocates every slot at "
                    f"worst-case context: TPU_DECODE_SLOTS="
                    f"{cfg.decode_slots} x TPU_MAX_MODEL_LEN="
                    f"{cfg.max_model_len} = {dense_rows} KV rows "
                    f"({dense_blocks} blocks of {cfg.kv_block_size} "
                    f"tokens), but the budget holds only {fit_blocks} "
                    "blocks after weights. Set KV_LAYOUT=paged to "
                    "admit by blocks actually in use (KV_BLOCK_SIZE="
                    f"{cfg.kv_block_size}), or lower TPU_DECODE_SLOTS "
                    "/ TPU_MAX_MODEL_LEN, enable WEIGHT_QUANT=int4 "
                    "(or int8) / KV_QUANT=int8, or raise TPU_TP_SIZE "
                    "to shard over more chips. " + tier_math)
            raise ValueError(
                f"Model + KV cache need {need / 2**30:.2f} GiB/device "
                f"but the HBM budget is {budget / 2**30:.2f} GiB "
                f"({limit / 2**30:.2f} GiB x TPU_HBM_UTILIZATION="
                f"{cfg.hbm_util}). {remedy}")
    return acct


def build_engine(cfg: Config) -> EngineBase:
    if cfg.llm_provider == "fake":  # internal/testing
        return FakeEngine()
    if cfg.llm_provider in ("vllm", "openai"):
        # "openai" = any OpenAI-compatible HTTP backend; same wire
        # protocol as vLLM. (The reference validated 'openai' but had no
        # handler for it — SURVEY.md §5 config notes.)
        from fasttalk_tpu.engine.remote import VLLMRemoteEngine

        return VLLMRemoteEngine(cfg.vllm_base_url, cfg.vllm_model,
                                api_key=cfg.vllm_api_key,
                                timeout_s=cfg.vllm_timeout,
                                max_inflight=cfg.remote_max_inflight,
                                admission_timeout_s=(
                                    cfg.sched_default_deadline_s),
                                connect_retries=(
                                    cfg.remote_connect_retries))
    if cfg.llm_provider == "ollama":
        from fasttalk_tpu.engine.remote import OllamaRemoteEngine

        return OllamaRemoteEngine(cfg.ollama_base_url, cfg.model_name,
                                  keep_alive=cfg.ollama_keep_alive,
                                  timeout_s=cfg.ollama_timeout,
                                  max_inflight=cfg.remote_max_inflight,
                                  admission_timeout_s=(
                                      cfg.sched_default_deadline_s),
                                  connect_retries=(
                                      cfg.remote_connect_retries))
    # Persistent compilation cache before the first compile: warmup's
    # executables reload from disk on repeat starts of the same config.
    from fasttalk_tpu.utils.compile_cache import enable_compilation_cache

    enable_compilation_cache(cfg.compile_cache, cfg.model_path)
    # Multi-host: bring up the JAX distributed runtime (DCN) before any
    # device use so meshes can span every host. No-op outside a
    # configured/pod environment. Lives here (not in the CLI) so bench,
    # `main.py test`, and library users all inherit it.
    from fasttalk_tpu.parallel.distributed import maybe_initialize

    maybe_initialize()
    model_cfg = get_model_config(cfg.model_name, cfg.model_path)
    dtype = _DTYPES.get(cfg.dtype, jnp.bfloat16)
    acct = check_hbm_budget(model_cfg, cfg, dtype,
                            n_devices=max(1, cfg.tp_size * cfg.dp_size
                                          * cfg.sp_size))
    log.info("HBM budget check passed",
             weight_gib=round(acct["weight_bytes_per_device"] / 2**30, 2),
             kv_gib=round(acct["kv_cache_bytes_per_device"] / 2**30, 2),
             limit_gib=round((acct["hbm_limit_bytes"] or 0) / 2**30, 2))
    mesh = put = raw_put = None
    if cfg.tp_size > 1 or cfg.dp_size > 1 or cfg.sp_size > 1:
        from fasttalk_tpu.parallel.mesh import make_mesh
        from fasttalk_tpu.parallel.sharding import param_put

        mesh = make_mesh(dp=cfg.dp_size, sp=cfg.sp_size,
                         tp=cfg.tp_size)
        # Weights go straight into their TP shards as they stream off
        # disk — a 70B checkpoint must never materialise on one chip.
        put = param_put(mesh, dtype)
        raw_put = param_put(mesh, None)
    weight_quant = _effective_weight_quant(cfg)
    if weight_quant in ("int8", "int4"):
        from fasttalk_tpu.ops.quant import quantizing_put

        import jax

        if put is None:
            put = lambda arr, path: jax.device_put(jnp.asarray(arr, dtype))  # noqa: E731
            raw_put = lambda arr, path: jax.device_put(jnp.asarray(arr))  # noqa: E731
        # Quantize host-side, tensor by tensor, before placement: device
        # HBM peaks at quantized bytes, not the transient bf16 copy.
        if weight_quant == "int4":
            # quantizing_put_int4 routes embed/lm_head through the int8
            # putter itself — hand it the un-wrapped puts.
            from fasttalk_tpu.quantization.int4 import (quantizing_put_int4,
                                                        validate_group)

            validate_group(model_cfg, cfg.weight_quant_group)
            put = quantizing_put_int4(put, raw_put, cfg.weight_quant_group)
        else:
            put = quantizing_put(put, raw_put)

    ckpt = find_checkpoint_dir(cfg.model_path, model_cfg.name) \
        if cfg.model_path else None
    if ckpt:
        from fasttalk_tpu.models.prepared_cache import (cache_meta,
                                                        load_prepared,
                                                        save_prepared)

        quant = weight_quant
        params = load_prepared(model_cfg, cfg.model_path, dtype, quant,
                               mesh, ckpt_dir=ckpt,
                               group=cfg.weight_quant_group)
        loaded = True
        if params is None:
            params = load_params(model_cfg, ckpt, dtype, put)
            if quant == "int8":
                log.info("Quantized matmul weights to int8 "
                         "(per-channel symmetric, host-side per tensor)")
            elif quant == "int4":
                log.info(
                    "Quantized layer matmuls to int4 (group-wise "
                    f"symmetric, group={cfg.weight_quant_group}, "
                    "data-free scales; run scripts/quantize_checkpoint.py "
                    "for AWQ-calibrated scales — its output lands in the "
                    "same prepared cache this load path reads)")
            # Cache the engine-ready pytree so the next restart skips
            # the whole safetensors->stack->cast->quantize->shard
            # pipeline (best-effort). An AWQ-calibrated cache written by
            # scripts/quantize_checkpoint.py has the same meta and wins
            # by already existing.
            save_prepared(params, cfg.model_path,
                          cache_meta(model_cfg, dtype, quant, mesh,
                                     ckpt_dir=ckpt,
                                     group=cfg.weight_quant_group))
    else:
        # No checkpoint: random init directly on the device(s) — zero
        # host->device weight transfer (models/loader.py).
        from fasttalk_tpu.models.loader import init_params_device

        log.warning(f"No checkpoint for {model_cfg.name!r} under "
                    f"{cfg.model_path!r}; using random-initialised weights")
        params, loaded = init_params_device(
            model_cfg, dtype, mesh=mesh, quantize=weight_quant,
            weight_quant_group=cfg.weight_quant_group), False
    tokenizer = load_tokenizer(cfg.model_path, cfg.model_name,
                               cfg.tokenizer_path,
                               template=model_cfg.chat_template)
    if not loaded and getattr(tokenizer, "vocab_size", 0) <= 512:
        # WEIGHT-FREE serving only (never when real weights loaded — a
        # checkpoint missing its tokenizer.json must not be silently
        # paired with an unrelated vocab): with no checkpoint tokenizer
        # the byte fallback inflates an English prompt ~6x (1
        # token/byte), which pushed weight-free benches into prefill
        # buckets real deployments never hit — burst TTFT then measured
        # tokenizer inflation, not the serving path
        # (scripts/profile_ttft.py). Prefer the bundled real 32k BPE
        # (scripts/make_bench_tokenizer.py) when the model vocab can
        # hold it.
        import os

        from fasttalk_tpu.engine.tokenizer import HFTokenizer

        bundled = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "assets", "bench_tokenizer.json")
        if os.path.isfile(bundled):
            cand = HFTokenizer(bundled, template=model_cfg.chat_template)
            if cand.vocab_size <= model_cfg.vocab_size:
                tokenizer = cand
    log.info(
        f"Building TPU engine: model={model_cfg.name} "
        f"({model_cfg.param_count() / 1e9:.2f}B params, "
        f"weights {'loaded' if loaded else 'random-init'}), "
        f"slots={cfg.decode_slots}, max_len={cfg.max_model_len}, "
        f"dtype={cfg.dtype}, weight_quant={weight_quant}, "
        f"kv_quant={cfg.kv_quant}, kv_layout={cfg.kv_layout}"
        + (f" ({acct['kv_pool_blocks']} x {cfg.kv_block_size}-token "
           f"blocks)" if cfg.kv_layout == "paged" else "")
        + f", mesh={dict(mesh.shape) if mesh else 'single-device'}")
    engine = TPUEngine(
        model_cfg, params, tokenizer,
        num_slots=cfg.decode_slots, max_len=cfg.max_model_len,
        prefill_chunk=cfg.prefill_chunk, dtype=dtype,
        context_window=min(cfg.default_context_window, cfg.max_model_len),
        mesh=mesh, use_pallas_attention=cfg.use_pallas_attention,
        use_pallas_int8=cfg.use_pallas_int8,
        weight_quant=weight_quant,
        weight_quant_group=cfg.weight_quant_group,
        use_pallas_int4=cfg.use_pallas_int4,
        steps_per_call=cfg.decode_steps_per_call,
        pipeline_depth=cfg.pipeline_depth,
        sampling_method=cfg.sampling,
        spec_decode=cfg.spec_decode,
        spec_draft_len=cfg.spec_draft_len,
        spec_breakeven=cfg.spec_breakeven,
        shared_prefix=cfg.shared_prefix,
        queue_bound=cfg.sched_queue_bound,
        default_deadline_s=cfg.sched_default_deadline_s,
        bulk_aging_s=cfg.sched_bulk_aging_s,
        kv_host_budget_mb=cfg.kv_host_budget_mb,
        kv_park_ttl_s=cfg.kv_park_ttl_s,
        kv_park_idle_s=cfg.kv_park_idle_s,
        kv_restore_min_tokens=cfg.kv_restore_min_tokens,
        kv_quant=cfg.kv_quant,
        kv_quant_granule=cfg.kv_quant_granule,
        kv_layout=cfg.kv_layout,
        kv_block_size=cfg.kv_block_size,
        kv_pool_blocks=acct["kv_pool_blocks"],
        kv_reserve_policy=cfg.kv_reserve_policy,
        kv_reserve_tokens=cfg.kv_reserve_tokens,
        kv_radix=cfg.kv_radix_enabled,
        kv_radix_min_blocks=cfg.kv_radix_min_blocks,
        kv_radix_evict_policy=cfg.kv_radix_evict_policy,
        structured=cfg.structured_mode,
        structured_max_states=cfg.structured_max_states,
        structured_state_budget=cfg.structured_state_budget,
        structured_jf_min=cfg.structured_jf_min,
        structured_cache=cfg.structured_cache,
        structured_json_depth=cfg.structured_json_depth)
    return engine
