"""Engine construction from service Config.

The device branch the reference routed through _detect_compute_device
(reference: app/utils/config.py:17-60) plus provider selection
(websocket_server_vllm.py:74-138) collapse here into one factory: the
``tpu`` provider builds the in-tree JAX engine on whatever platform JAX
has (TPU in production, CPU in tests); ``fake`` builds the test engine.
"""

from __future__ import annotations

import jax.numpy as jnp

from fasttalk_tpu.engine.engine import EngineBase, TPUEngine
from fasttalk_tpu.engine.fake import FakeEngine
from fasttalk_tpu.engine.tokenizer import load_tokenizer
from fasttalk_tpu.models.configs import get_model_config
from fasttalk_tpu.models.loader import load_or_init
from fasttalk_tpu.utils.config import Config
from fasttalk_tpu.utils.logger import get_logger

log = get_logger("engine.factory")

_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
           "float16": jnp.float16}


def build_engine(cfg: Config) -> EngineBase:
    if cfg.llm_provider == "fake":  # internal/testing
        return FakeEngine()
    if cfg.llm_provider in ("vllm", "openai"):
        # "openai" = any OpenAI-compatible HTTP backend; same wire
        # protocol as vLLM. (The reference validated 'openai' but had no
        # handler for it — SURVEY.md §5 config notes.)
        from fasttalk_tpu.engine.remote import VLLMRemoteEngine

        return VLLMRemoteEngine(cfg.vllm_base_url, cfg.vllm_model,
                                api_key=cfg.vllm_api_key,
                                timeout_s=cfg.vllm_timeout)
    if cfg.llm_provider == "ollama":
        from fasttalk_tpu.engine.remote import OllamaRemoteEngine

        return OllamaRemoteEngine(cfg.ollama_base_url, cfg.model_name,
                                  keep_alive=cfg.ollama_keep_alive,
                                  timeout_s=cfg.ollama_timeout)
    model_cfg = get_model_config(cfg.model_name)
    dtype = _DTYPES.get(cfg.dtype, jnp.bfloat16)
    mesh = put = None
    if cfg.tp_size > 1 or cfg.dp_size > 1:
        from fasttalk_tpu.parallel.mesh import make_mesh
        from fasttalk_tpu.parallel.sharding import param_put

        mesh = make_mesh(dp=cfg.dp_size, tp=cfg.tp_size)
        # Weights go straight into their TP shards as they stream off
        # disk — a 70B checkpoint must never materialise on one chip.
        put = param_put(mesh, dtype)
    params, loaded = load_or_init(model_cfg, cfg.model_path, dtype, put=put)
    tokenizer = load_tokenizer(cfg.model_path, cfg.model_name,
                               cfg.tokenizer_path,
                               template=model_cfg.chat_template)
    log.info(
        f"Building TPU engine: model={model_cfg.name} "
        f"({model_cfg.param_count() / 1e9:.2f}B params, "
        f"weights {'loaded' if loaded else 'random-init'}), "
        f"slots={cfg.decode_slots}, max_len={cfg.max_model_len}, "
        f"dtype={cfg.dtype}, "
        f"mesh={dict(mesh.shape) if mesh else 'single-device'}")
    engine = TPUEngine(
        model_cfg, params, tokenizer,
        num_slots=cfg.decode_slots, max_len=cfg.max_model_len,
        prefill_chunk=cfg.prefill_chunk, dtype=dtype,
        context_window=min(cfg.default_context_window, cfg.max_model_len),
        mesh=mesh, use_pallas_attention=cfg.use_pallas_attention,
        steps_per_call=cfg.decode_steps_per_call,
        pipeline_depth=cfg.pipeline_depth)
    return engine
